"""VolumeBinding filter kernel (SURVEY.md §2 C7).

The reference's VolumeBinding plugin (expected
`framework/plugins/volumebinding/` — [UNVERIFIED], mount empty) decides,
per pod per node, whether the pod's PVCs can be satisfied there:

  - a BOUND PVC restricts the pod to nodes satisfying its PV's
    nodeAffinity (zone/hostname-restricted volumes);
  - an UNBOUND WaitForFirstConsumer PVC needs either an available static
    PV (class + capacity + nodeAffinity match) or dynamic provisioning
    whose storage-class allowedTopologies admit the node;
  - a missing PVC or an unbound Immediate-mode PVC makes the pod
    unschedulable (upstream UnschedulableAndUnresolvable).

TPU-native shape: PV nodeAffinity terms compile through the SAME
requirement machinery as pod node-affinity (encoder interns them into
`rq_exprs`), so the per-PV node masks are rows of the shared [Rq, N]
requirement table. The static-candidate test batches into one
[P*MVol, V] x [V, N] matmul; everything is gated on the `has_volumes`
capability flag, so volume-free clusters never trace any of it.

Same-cycle contention for one static PV IS arbitrated in-cycle
(VERDICT r2 item 8): the VolumeBinding plugin carries a `pv_claimed`
bitmap through the commit engines' extra state — a placed pod claims its
chosen PV (lowest-index compatible, upstream's deterministic binder
choice), later pods in the cycle see the PV as unavailable, and the
rounds engine's participant table additionally resolves SAME-ROUND
claimants of one PV by rank (`_RB_PV`). Dynamic provisioning is
unlimited and needs no arbitration.

Multi-volume pods are admitted JOINTLY (Hall's condition, `_hall_ok`)
and claim with the SDR-SAFE choice (`chosen_pv_sdr`): each slot takes
the lowest-index PV whose removal keeps Hall's condition over the pod's
remaining static-needy slots — exact for ANY candidate-set shape by the
systems-of-distinct-representatives argument. Plain slot-order greedy
CAN dead-end even on the nested (class + capacity-threshold at one
node) sets the current encoder produces — see the 3-slot chain test;
the SDR rule is what makes claiming exact, and unlike the older
constrained-count-first ordering it stays exact for crossing sets too.
The subset enumeration is capped beyond 7 slots, where per-pod
dominance groups keep laminar families exact (PARITY #8 residual is
crossing sets beyond 7 slots only).
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp

from . import labels as labels_ops

_CAP_EPS = 1e-3


def pv_node_table(snap, expr_mask):  # bool [V, N]
    """Per-PV node admissibility (nodeAffinity through the shared
    requirement table) AND pre-cycle availability."""
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)  # [Rq, N]
    return (
        labels_ops.take_rows(req, snap.pv_req_id, True)
        & snap.pv_avail[:, None]
    )


def pod_pv_cand(snap, j):  # bool [P, V] class+size candidacy for slot j
    cls = snap.pod_vol_class[:, j]
    size = snap.pod_vol_size[:, j]
    return (
        (snap.pv_class[None, :] == cls[:, None])
        & (snap.pv_capacity[None, :] + _CAP_EPS >= size[:, None])
        & (snap.pod_vol_mode[:, j] == 1)[:, None]
    )


def _hall_subsets(MVol: int):
    """Slot subsets (size >= 2) whose Hall condition the joint-admission
    check enumerates. Exact (all subsets) up to MVol=6; beyond that the
    2^MVol matmul count would explode compile and device time, so only
    pairs + the full set are statically enumerated and the per-pod
    DOMINANCE GROUPS (_dominance_anchors) cover the rest — for LAMINAR
    candidate families (everything the class + capacity-threshold model
    can produce) the Hall-tight subsets are exactly the dominance
    groups, so the capped regime stays exact at any slot count; only
    >6-slot pods with CROSSING sets (not currently producible) retain a
    residual (PARITY #8). MVol is a sticky pad dim with bucket 2; real
    pods rarely mount > 4 PVCs."""
    if MVol <= 6:
        sizes = range(2, MVol + 1)
    else:
        return [
            *itertools.combinations(range(MVol), 2),
            tuple(range(MVol)),
        ]
    return [
        s for r in sizes for s in itertools.combinations(range(MVol), r)
    ]


def _membership(cands, a, t):
    """bool [...]: is slot t's candidate set contained in slot a's, per
    pod — the dominance-group membership test (A ⊆ B on the claimable
    PV sets; inclusion on the raw sets implies inclusion on any common
    node/claim restriction)."""
    return ~jnp.any(cands[t] & ~cands[a], axis=-1)


def _hall_ok(pv_ok_f, cands, dyn_oks, modes, ok):
    """Joint feasibility across a pod's unbound volume slots (PARITY #8
    closure): the per-slot static_ok tests admit a pod whose two PVCs
    are satisfiable only by the SAME single PV; binding then fails at
    the agent. Exact fix via Hall's theorem: an assignment of DISTINCT
    PVs to the pod's static-required slots exists iff for every subset
    of slots, the union of their candidate PV sets (restricted to PVs
    usable on the node) has at least |subset| members. Slots that can
    ride dynamic provisioning on the node never constrain (their
    subsets are dominated by the pure-static sub-subsets, enumerated
    too). Singletons are the existing per-slot test, so only subsets of
    size >= 2 are added — one [P,V]x[V,N] count matmul each. Beyond 6
    slots the static enumeration is capped and per-pod DOMINANCE GROUPS
    take over (exact for laminar families — see _hall_subsets). The
    single-pod [N]-scale twin lives in volume_mask_unbound_row; keep
    the two in lockstep."""
    MVol = len(cands)
    for s in _hall_subsets(MVol):
        u = cands[s[0]]
        for j in s[1:]:
            u = u | cands[j]
        avail = u.astype(jnp.float32) @ pv_ok_f  # [P, N] counts
        need = sum(
            ((modes[j] == 1)[:, None] & ~dyn_oks[j]).astype(jnp.int32)
            for j in s
        )
        ok &= avail + 0.5 >= need.astype(jnp.float32)
    if MVol > 6:
        # dominance groups, one per anchor slot: members are the slots
        # whose candidate set is CONTAINED in the anchor's, need counts
        # the static-needy members — for laminar families every
        # Hall-tight subset is such a group (the down-set of its
        # largest member), so this keeps the capped regime exact. The
        # group union IS the anchor's set (members are subsets of it),
        # so avail is one anchor matmul, no union accumulation.
        for a in range(MVol):
            need = None
            for t in range(MVol):
                member = _membership(cands, a, t)  # [P]
                n_t = (
                    member[:, None]
                    & (modes[t] == 1)[:, None]
                    & ~dyn_oks[t]
                ).astype(jnp.int32)
                need = n_t if need is None else need + n_t
            avail = cands[a].astype(jnp.float32) @ pv_ok_f
            ok &= avail + 0.5 >= need.astype(jnp.float32)
    return ok


def volume_mask(snap, expr_mask: jnp.ndarray,
                pv_claimed: jnp.ndarray | None = None) -> jnp.ndarray:
    """Conjunction over each pod's PVC constraints -> bool [P, N].
    `pv_claimed` (bool [V]) marks static PVs already claimed by this
    cycle's placements; None = pre-cycle availability only (the static
    phase — the commit engines re-run the unbound-slot part per round
    with the live bitmap via VolumeBinding.dyn_mask*)."""
    P, N = snap.P, snap.N
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)  # [Rq, N]

    def req_rows(ids):  # i32 [X] -> bool [X, N]; id < 0 -> all-True
        return labels_ops.take_rows(req, ids, True)

    pv_ok = req_rows(snap.pv_req_id) & snap.pv_avail[:, None]  # [V, N]
    if pv_claimed is not None:
        pv_ok = pv_ok & ~pv_claimed[:, None]
    pv_ok_f = pv_ok.astype(jnp.float32)
    MVol = snap.pod_vol_mode.shape[1]

    ok = jnp.ones((P, N), bool)
    cands, dyn_oks, modes = [], [], []
    for j in range(MVol):
        mode = snap.pod_vol_mode[:, j]  # [P]
        rid = snap.pod_vol_req[:, j]

        rid_rows = req_rows(rid)  # [P, N] (bound PV affinity / dyn topology)

        # static candidates: available PVs of the right class and size,
        # usable on the node
        cand = pod_pv_cand(snap, j)  # [P, V]
        static_ok = (cand.astype(jnp.float32) @ pv_ok_f) > 0.0  # [P, N]

        dyn_ok = jnp.where(
            (rid == -2)[:, None], False, rid_rows
        )  # -1 folds to all-True via req_rows
        row_ok = jnp.where(
            (mode == 0)[:, None],
            rid_rows,
            jnp.where((mode == 1)[:, None], static_ok | dyn_ok, False),
        )
        ok &= jnp.where((mode >= 0)[:, None], row_ok, True)
        cands.append(cand)
        dyn_oks.append(dyn_ok)
        modes.append(mode)
    if MVol >= 2 and snap.has_multi_volume:
        ok = _hall_ok(pv_ok_f, cands, dyn_oks, modes, ok)
    return ok


def volume_mask_unbound(snap, expr_mask, pv_claimed) -> jnp.ndarray:
    """The CLAIM-dependent residue of volume_mask: only unbound
    WaitForFirstConsumer slots (mode==1) re-evaluate against the live
    `pv_claimed` bitmap; everything else (bound-PV affinity, missing
    PVCs) is claim-independent and already in the static mask."""
    P, N = snap.P, snap.N
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)
    pv_ok = (
        labels_ops.take_rows(req, snap.pv_req_id, True)
        & snap.pv_avail[:, None]
        & ~pv_claimed[:, None]
    )  # [V, N]
    pv_ok_f = pv_ok.astype(jnp.float32)
    MVol = snap.pod_vol_mode.shape[1]
    ok = jnp.ones((P, N), bool)
    cands, dyn_oks, modes = [], [], []
    for j in range(MVol):
        mode = snap.pod_vol_mode[:, j]
        rid = snap.pod_vol_req[:, j]
        cand = pod_pv_cand(snap, j)
        static_ok = (cand.astype(jnp.float32) @ pv_ok_f) > 0.0
        dyn_ok = jnp.where(
            (rid == -2)[:, None], False,
            labels_ops.take_rows(req, rid, True),
        )
        ok &= jnp.where((mode == 1)[:, None], static_ok | dyn_ok, True)
        cands.append(cand)
        dyn_oks.append(dyn_ok)
        modes.append(mode)
    if MVol >= 2 and snap.has_multi_volume:
        ok = _hall_ok(pv_ok_f, cands, dyn_oks, modes, ok)
    return ok


def volume_mask_unbound_row(snap, expr_mask, pv_claimed, p):
    """Single-pod row of volume_mask_unbound (bool [N]) — the scan
    engine's per-step hook; the batched form would redo [P, N] work at
    every one of P scan steps."""
    N = snap.N
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)
    pv_ok = (
        labels_ops.take_rows(req, snap.pv_req_id, True)
        & snap.pv_avail[:, None]
        & ~pv_claimed[:, None]
    )  # [V, N]
    Rq = req.shape[0]
    MVol = snap.pod_vol_mode.shape[1]
    ok = jnp.ones((N,), bool)
    cands, dyn_oks, modes = [], [], []
    for j in range(MVol):
        mode = snap.pod_vol_mode[p, j]
        rid = snap.pod_vol_req[p, j]
        cand = (
            (snap.pv_class == snap.pod_vol_class[p, j])
            & (snap.pv_capacity + _CAP_EPS >= snap.pod_vol_size[p, j])
            & (mode == 1)
        )  # [V]
        static_ok = jnp.any(cand[:, None] & pv_ok, axis=0)  # [N]
        rid_row = jnp.where(
            rid >= 0, req[jnp.clip(rid, 0, Rq - 1)], True
        )
        dyn_ok = jnp.where(rid == -2, False, rid_row)
        ok &= jnp.where(mode == 1, static_ok | dyn_ok, True)
        cands.append(cand)
        dyn_oks.append(dyn_ok)
        modes.append(mode)
    if MVol >= 2 and snap.has_multi_volume:
        # Hall's condition over this pod's slots — the single-pod
        # [N]-scale twin of _hall_ok (same subsets via _hall_subsets
        # plus the capped-regime dominance groups; keep in lockstep)
        for sub in _hall_subsets(MVol):
            u = cands[sub[0]]
            for j in sub[1:]:
                u = u | cands[j]
            avail = jnp.sum(
                u[:, None] & pv_ok, axis=0, dtype=jnp.int32
            )  # [N]
            need = sum(
                ((modes[j] == 1) & ~dyn_oks[j]).astype(jnp.int32)
                for j in sub
            )
            ok &= avail >= need
        if MVol > 6:
            # group union == anchor set, like _hall_ok
            for a in range(MVol):
                need = None
                for t in range(MVol):
                    member = _membership(cands, a, t)  # scalar bool
                    n_t = (
                        member & (modes[t] == 1) & ~dyn_oks[t]
                    ).astype(jnp.int32)
                    need = n_t if need is None else need + n_t
                avail = jnp.sum(
                    cands[a][:, None] & pv_ok, axis=0, dtype=jnp.int32
                )  # [N]
                ok &= avail >= need
    return ok


def _sdr_other_subsets(MVol: int, j: int):
    """Subsets (size >= 1) of the slots other than `j` whose Hall margin
    the SDR-safe choice checks. Exact (all subsets) while the
    enumeration stays small; beyond 6 remaining slots only singletons +
    pairs + the full rest are statically enumerated and _sdr_safe_choice
    adds the per-pod dominance groups, which keep the capped regime
    exact for laminar candidate families at any slot count (crossing
    sets beyond 7 slots remain a PARITY #8 residual)."""
    others = [t for t in range(MVol) if t != j]
    if len(others) <= 6:
        return [
            s
            for r in range(1, len(others) + 1)
            for s in itertools.combinations(others, r)
        ]
    return [
        *itertools.combinations(others, 1),
        *itertools.combinations(others, 2),
        tuple(others),
    ]


def _sdr_safe_choice(cand_j, cands, needy, dyn_j, MVol, j):
    """SDR-preserving candidate choice for slot j, batched over pods.

    cand_j bool [P, V]: slot j's claimable PVs (already node-admissible,
    unclaimed, active-masked). cands: per-slot [P, V] claimable sets.
    needy bool [P, MVol]: pending slots that REQUIRE a static PV (no
    dynamic ride at this node). dyn_j bool [P]: slot j can ride dynamic.

    Rule (exact by the classic systems-of-distinct-representatives
    argument): claim the LOWEST-INDEX v in cand_j whose removal keeps
    Hall's condition over every subset of the other pending needy slots
    — i.e. v is unsafe iff some subset s has margin avail(s) - need(s)
    <= 0 and v lies in s's candidate union. When Hall holds for the
    needy slots, a safe v always exists for a needy slot; a dyn-capable
    slot with no safe v rides dynamic (-1) instead of stealing; a needy
    slot with no safe v (the pod is already beyond Hall's guarantee,
    e.g. same-pass contention losses) falls back to the lowest
    candidate, matching the old greedy behavior."""
    P, V = cand_j.shape
    unsafe = jnp.zeros((P, V), bool)
    for s in _sdr_other_subsets(MVol, j):
        u = jnp.zeros((P, V), bool)
        need = jnp.zeros((P,), jnp.int32)
        for t in s:
            u = u | (cands[t] & needy[:, t][:, None])
            need = need + needy[:, t].astype(jnp.int32)
        avail = jnp.sum(u, axis=1, dtype=jnp.int32)
        unsafe = unsafe | (u & (avail <= need)[:, None])
    others = [t for t in range(MVol) if t != j]
    if len(others) > 6:
        # capped static enumeration: per-pod dominance groups cover the
        # mid-size subsets (exact for laminar candidate families — see
        # _hall_subsets; a group is the needy down-set of its anchor).
        # NOTE the union is needy-masked like this function's static-
        # subset loop above; _hall_ok's group union deliberately seeds
        # the anchor's full set to match ITS static-subset convention.
        for a in others:
            u = jnp.zeros((P, V), bool)
            need = jnp.zeros((P,), jnp.int32)
            for t in others:
                member = _membership(cands, a, t) & needy[:, t]
                u = u | (cands[t] & member[:, None])
                need = need + member.astype(jnp.int32)
            avail = jnp.sum(u, axis=1, dtype=jnp.int32)
            unsafe = unsafe | (u & (avail <= need)[:, None])
    safe = cand_j & ~unsafe
    ids = jnp.arange(V, dtype=jnp.int32)[None, :]
    best_safe = jnp.min(jnp.where(safe, ids, V), axis=1).astype(jnp.int32)
    best_any = jnp.min(jnp.where(cand_j, ids, V), axis=1).astype(jnp.int32)
    chosen = jnp.where(
        best_safe < V,
        best_safe,
        jnp.where(dyn_j, -1, jnp.where(best_any < V, best_any, -1)),
    )
    return chosen


def _dyn_at_node(snap, expr_mask, node_of):  # bool [P, MVol]
    """Whether each volume slot can ride dynamic provisioning at the
    pod's chosen node (storage-class allowedTopologies admit it)."""
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)  # [Rq, N]
    Rq = req.shape[0]
    nsafe = jnp.clip(node_of, 0, snap.N - 1)
    req_at = req[:, nsafe].T  # [P, Rq]
    rid = snap.pod_vol_req  # [P, MVol]
    picked = jnp.take_along_axis(
        req_at, jnp.clip(rid, 0, Rq - 1), axis=1
    )  # [P, MVol]
    return jnp.where(rid == -2, False, jnp.where(rid >= 0, picked, True))


def chosen_pv_row(snap, expr_mask, pv_claimed, node, p, j):
    """Scalar chosen_pv for one pod at one node (the scan engine's
    per-step claim): i32 [] PV index or -1."""
    V = snap.pv_avail.shape[0]
    pv_ok_n = (
        pv_node_table(snap, expr_mask)[:, jnp.clip(node, 0, snap.N - 1)]
        & ~pv_claimed
    )  # [V]
    cand = (
        (snap.pv_class == snap.pod_vol_class[p, j])
        & (snap.pv_capacity + _CAP_EPS >= snap.pod_vol_size[p, j])
        & (snap.pod_vol_mode[p, j] == 1)
        & pv_ok_n
    )
    idx = jnp.where(cand, jnp.arange(V, dtype=jnp.int32), V)
    best = jnp.min(idx).astype(jnp.int32)
    return jnp.where(best < V, best, -1)


def fold_pv_claims(snap, expr_mask, pv_claimed, accepted, node_of,
                   rank):
    """Fold a BATCH of placements' static-PV claims into `pv_claimed`
    exactly as a rank-ordered sequential pass would: iterate — each pass
    every unresolved claimant picks its lowest-index compatible
    unclaimed PV, and only the LOWEST-RANK claimant per contended PV
    claims it; losers retry against the updated bitmap. Terminates in at
    most V passes (each pass claims >= 1 PV or nothing changes); when
    the batch is known claim-disjoint (the rounds engine's _RB_PV guard
    guarantees it) the loop exits after one pass.

    Within a pod, slots claim in index order with the SDR-SAFE choice
    (chosen_pv_sdr): greedy lowest-index claiming can dead-end — slot A
    {pv0, pv1} takes pv0 before slot B {pv0} — even though the
    Hall-condition mask admitted the pod because a distinct assignment
    exists. The SDR rule (claim the lowest PV whose removal keeps
    Hall's condition over the remaining needy slots) is EXACT for any
    slot count the subset enumeration covers (all of MVol <= 7; capped
    beyond — PARITY #8)."""
    V = snap.pv_avail.shape[0]
    P = accepted.shape[0]
    MVol = snap.pod_vol_mode.shape[1]
    big = jnp.int32(2**31 - 1)
    multi = MVol >= 2 and snap.has_multi_volume

    def body(carry):
        claimed, pending_slots, _progress = carry
        progress = jnp.zeros((), bool)
        for j in range(MVol):
            if multi:
                ch = chosen_pv_sdr(
                    snap, expr_mask, claimed, node_of, pending_slots, j
                )  # [P]
            else:
                ch = chosen_pv(
                    snap, expr_mask, claimed, node_of,
                    pending_slots[:, j], j,
                )  # [P]
            has = ch >= 0
            chc = jnp.clip(ch, 0, V - 1)
            # lowest rank per chosen PV wins this pass
            winner_rank = (
                jnp.full((V,), big).at[chc].min(
                    jnp.where(has, rank, big)
                )
            )
            won = has & (rank == winner_rank[chc])
            claimed = claimed.at[chc].max(won)
            # winners' slots resolve; losers retry next pass
            pending_slots = pending_slots.at[:, j].set(
                pending_slots[:, j] & ~won & has
            )
            progress = progress | jnp.any(won)
        return claimed, pending_slots, progress

    def cond(carry):
        _, pending_slots, progress = carry
        return progress & jnp.any(pending_slots)

    init_slots = jnp.broadcast_to(accepted[:, None], (P, MVol)) & (
        snap.pod_vol_mode == 1
    )
    claimed, _, _ = jax.lax.while_loop(
        cond,
        body,
        body((pv_claimed, init_slots, jnp.ones((), bool))),
    )
    return claimed


def chosen_pv(snap, expr_mask, pv_claimed, node_of, active, j):
    """i32 [P]: the PV each active pod would claim for volume slot j at
    node `node_of` — the LOWEST-INDEX compatible available unclaimed PV
    admissible on that node; -1 when the slot is not an unbound static
    claim (incl. pods whose slot rides dynamic provisioning because no
    static PV fits). SINGLE-VOLUME path only: with one slot per pod the
    lowest-index choice is the deterministic binder choice both engines
    and the oracle share; multi-volume pods use chosen_pv_sdr, whose
    Hall-margin-preserving choice avoids the intra-pod dead-ends greedy
    lowest-index claiming can hit."""
    V = snap.pv_avail.shape[0]
    pv_ok = (
        pv_node_table(snap, expr_mask) & ~pv_claimed[:, None]
    )  # [V, N]
    nsafe = jnp.clip(node_of, 0, snap.N - 1)
    at_node = pv_ok[:, nsafe].T  # [P, V]
    cand = pod_pv_cand(snap, j) & at_node & active[:, None]
    idx = jnp.where(cand, jnp.arange(V, dtype=jnp.int32)[None, :], V)
    best = jnp.min(idx, axis=1).astype(jnp.int32)
    return jnp.where(best < V, best, -1)


def chosen_pv_sdr(snap, expr_mask, pv_claimed, node_of, pending_slots, j,
                  mine=None):
    """i32 [P]: the SDR-safe claim for slot j (see _sdr_safe_choice) —
    chosen_pv's multi-volume replacement. `pending_slots` (bool
    [P, MVol]) marks unresolved unbound-static slots; the OTHER pending
    needy slots define the Hall margins the choice must preserve.
    `mine` (bool [P, V] or None) additionally excludes PVs this pod
    already claimed in the same resolution pass (intra-pod
    distinctness for the contention-free guard simulation)."""
    MVol = snap.pod_vol_mode.shape[1]
    pvt = pv_node_table(snap, expr_mask) & ~pv_claimed[:, None]  # [V, N]
    nsafe = jnp.clip(node_of, 0, snap.N - 1)
    at_node = pvt[:, nsafe].T  # [P, V]
    if mine is not None:
        at_node = at_node & ~mine
    dyn = _dyn_at_node(snap, expr_mask, node_of)  # [P, MVol]
    cands = [pod_pv_cand(snap, t) & at_node for t in range(MVol)]
    needy = pending_slots & (snap.pod_vol_mode == 1) & ~dyn  # [P, MVol]
    active = pending_slots[:, j]
    cand_j = cands[j] & active[:, None]
    ch = _sdr_safe_choice(cand_j, cands, needy, dyn[:, j], MVol, j)
    return jnp.where(active, ch, -1)


def chosen_pv_slots(snap, expr_mask, pv_claimed, node_of, active):
    """i32 [P, MVol]: the claims a CONTENTION-FREE fold pass would make
    for each active pod — slots in index order, SDR-safe choice when
    multi-volume, intra-pod distinctness via a per-pod `mine` bitmap.
    The rounds engine's _RB_PV guard key (must predict fold_pv_claims's
    first-pass behavior so claim-disjoint batches fold in one pass)."""
    MVol = snap.pod_vol_mode.shape[1]
    V = snap.pv_avail.shape[0]
    P = node_of.shape[0]
    multi = MVol >= 2 and snap.has_multi_volume
    pending = jnp.broadcast_to(active[:, None], (P, MVol)) & (
        snap.pod_vol_mode == 1
    )
    mine = jnp.zeros((P, V), bool)
    out = []
    for j in range(MVol):
        if multi:
            ch = chosen_pv_sdr(
                snap, expr_mask, pv_claimed, node_of, pending, j, mine=mine
            )
        else:
            ch = chosen_pv(
                snap, expr_mask, pv_claimed, node_of, pending[:, j], j
            )
        out.append(ch)
        has = ch >= 0
        chc = jnp.clip(ch, 0, V - 1)
        mine = mine.at[jnp.arange(P), chc].max(has)
        pending = pending.at[:, j].set(False)
    return jnp.stack(out, axis=1)


def chosen_pv_sdr_row(snap, expr_mask, pv_claimed, node, p, pending_row,
                      j):
    """Single-pod [V]-scale twin of chosen_pv_sdr (the scan engine's
    per-step claim; keep in lockstep)."""
    MVol = snap.pod_vol_mode.shape[1]
    V = snap.pv_avail.shape[0]
    nsafe = jnp.clip(node, 0, snap.N - 1)
    at_node = pv_node_table(snap, expr_mask)[:, nsafe] & ~pv_claimed  # [V]
    req = labels_ops.requirement_mask(snap.rq_exprs, expr_mask)
    Rq = req.shape[0]
    req_at = req[:, nsafe]  # [Rq]
    rid = snap.pod_vol_req[p]  # [MVol]
    picked = req_at[jnp.clip(rid, 0, Rq - 1)]
    dyn = jnp.where(rid == -2, False, jnp.where(rid >= 0, picked, True))
    mode = snap.pod_vol_mode[p]  # [MVol]

    def cand_row(t):
        return (
            (snap.pv_class == snap.pod_vol_class[p, t])
            & (snap.pv_capacity + _CAP_EPS >= snap.pod_vol_size[p, t])
            & (mode[t] == 1)
            & at_node
        )  # [V]

    cands = [cand_row(t)[None, :] for t in range(MVol)]  # [1, V] each
    needy = (pending_row & (mode == 1) & ~dyn)[None, :]  # [1, MVol]
    active = pending_row[j]
    cand_j = cands[j] & active
    ch = _sdr_safe_choice(cand_j, cands, needy, dyn[j][None], MVol, j)[0]
    return jnp.where(active, ch, -1)
