from .resources import (  # noqa: F401
    MAX_NODE_SCORE,
    balanced_allocation_score,
    fit_mask,
    least_requested_score,
)
from .commit import CommitResult, greedy_commit  # noqa: F401
