"""Taint/toleration kernels.

The reference's `TaintToleration` Filter/Score plugin walks each node's
taints per pod (`framework/plugins/tainttoleration/` — [UNVERIFIED], mount
empty; SURVEY.md §2 C7/C8). TPU-native design: taint sets and toleration
sets are deduplicated at encode time (clusters have FEW distinct taint/
toleration combinations), one small kernel computes the [Tl, Ts]
set-compatibility tables, and the per-(pod, node) masks are a 2-D int
gather — O(Tl*Ts*slots) + O(P*N) gather instead of O(P*N*taints*tols).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..models import encoding as enc


def toleration_tables(snap) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (schedulable [Tl, Ts] bool, prefer_untolerated [Tl, Ts] f32).

    schedulable: every NoSchedule/NoExecute taint in set Ts is tolerated by
    set Tl (v1helper.TolerationsTolerateTaint semantics: effect matches or
    toleration effect empty; key matches or toleration key empty with
    Exists; value matches unless operator Exists).
    prefer_untolerated: count of PreferNoSchedule taints not tolerated
    (the TaintToleration score input)."""
    # toleration axes: [Tl, MTl]; taint axes: [Ts, MTt]
    tl_key = snap.tl_key[:, None, :, None]  # [Tl, 1, MTl, 1]
    tl_op = snap.tl_op[:, None, :, None]
    tl_val = snap.tl_val[:, None, :, None]
    tl_eff = snap.tl_effect[:, None, :, None]
    tl_ok = snap.tl_valid[:, None, :, None]
    ts_key = snap.ts_key[None, :, None, :]  # [1, Ts, 1, MTt]
    ts_val = snap.ts_val[None, :, None, :]
    ts_eff = snap.ts_effect[None, :, None, :]
    ts_ok = snap.ts_valid[None, :, None, :]

    effect_match = (tl_eff == -1) | (tl_eff == ts_eff)
    key_match = jnp.where(
        tl_key == -1,
        tl_op == enc.TOL_OP_EXISTS,  # empty key requires Exists, matches all
        tl_key == ts_key,
    )
    value_match = (tl_op == enc.TOL_OP_EXISTS) | (tl_val == ts_val)
    tolerates = tl_ok & effect_match & key_match & value_match
    # taint t tolerated by ANY toleration slot: reduce over MTl
    tolerated = tolerates.any(axis=2)  # [Tl, Ts, MTt]

    hard = ts_ok[:, :, 0, :] & (
        (ts_eff[:, :, 0, :] == enc.EFFECT_NO_SCHEDULE)
        | (ts_eff[:, :, 0, :] == enc.EFFECT_NO_EXECUTE)
    )  # [1, Ts, MTt]
    schedulable = (~hard | tolerated).all(axis=-1)  # [Tl, Ts]

    prefer = ts_ok[:, :, 0, :] & (ts_eff[:, :, 0, :] == enc.EFFECT_PREFER_NO_SCHEDULE)
    prefer_untolerated = jnp.sum(prefer & ~tolerated, axis=-1).astype(jnp.float32)
    return schedulable, prefer_untolerated


def _pair_lookup(table, row_ids, col_ids) -> jnp.ndarray:
    """table[row_ids[p], col_ids[n]] for all (p, n), WITHOUT the [P, N]
    arbitrary-index gather (a single such gather costs ~0.4s at 10k x 5k
    on TPU — scalar access pattern). Two one-hot matmuls ride the MXU
    instead: [P, A] @ [A, B] -> [P, B] @ [B, N]."""
    A, B = table.shape
    oh_rows = jax.nn.one_hot(row_ids, A, dtype=jnp.float32)  # [P, A]
    rows = oh_rows @ table.astype(jnp.float32)  # [P, B]
    oh_cols = jax.nn.one_hot(col_ids, B, dtype=jnp.float32)  # [N, B]
    return rows @ oh_cols.T  # [P, N]


def taint_filter_mask(snap) -> jnp.ndarray:  # bool [P, N]
    schedulable, _ = toleration_tables(snap)
    return _pair_lookup(
        schedulable, snap.pod_tolset, snap.node_taintset
    ) > 0.5


def taint_score(snap) -> jnp.ndarray:  # f32 [P, N] in [0, 100]
    """TaintToleration score: fewer untolerated PreferNoSchedule taints is
    better, normalized like upstream DefaultNormalizeScore(reverse=true):
    score = (1 - count / max_count_over_nodes) * 100, with 100 when no node
    has such taints. Deviation (documented): the max is over ALL nodes, not
    just filter-feasible ones (the oracle does the same)."""
    _, prefer = toleration_tables(snap)
    counts = _pair_lookup(prefer, snap.pod_tolset, snap.node_taintset)
    counts = jnp.where(snap.node_valid[None, :], counts, 0.0)
    mx = jnp.max(counts, axis=1, keepdims=True)  # [P, 1]
    return jnp.where(mx > 0, (1.0 - counts / jnp.maximum(mx, 1e-9)) * 100.0, 100.0)
