"""tpu-scheduler: a TPU-native cluster-scheduling framework.

A from-scratch re-design of the kube-scheduler family that
`yinwoods/k8s-scheduler` derives from (see SURVEY.md for the blueprint and
its provenance caveats: the reference mount was empty, so parity targets come
from the surveyed upstream architecture, tagged [UNVERIFIED] there).

Design in one paragraph: instead of the reference's per-pod `ScheduleOne`
loop (pop one pod, run Filter plugins over nodes on 16 goroutines, score,
bind), the whole pending set is scheduled per cycle as ONE batched JAX/XLA
program. Filter plugins become boolean mask kernels over a pods x nodes
feasibility matrix, Score plugins become vmapped scoring kernels combined by
weight, and the reference's sequential state mutation between pods is
preserved exactly by a greedy commit `lax.scan` over the priority-ordered
pending set (running allocatable subtraction + running topology-domain
counts). Preemption is a batched what-if over per-node victim prefixes; gang
scheduling is group-feasibility + all-or-nothing commit. The host side keeps
the reference's shape: SchedulerCache (assume/confirm/forget), a
SchedulingQueue (active/backoff/unschedulable), a plugin registry with the
upstream extension points, upstream config knobs, and a gRPC shim that takes
cluster snapshots in and returns the whole queue's bindings in one shot.
"""

__version__ = "0.1.0"
