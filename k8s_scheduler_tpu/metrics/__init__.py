from .metrics import SchedulerMetrics, global_metrics

__all__ = ["SchedulerMetrics", "global_metrics"]
