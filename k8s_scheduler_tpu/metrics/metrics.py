"""Observability: Prometheus metrics with the upstream metric names.

The reference family registers its metrics in `metrics/metrics.go`
([UNVERIFIED] location, mount empty; SURVEY.md §2 C13, §5.5) under the
`scheduler_` subsystem. This module keeps the same names so existing
dashboards and alerts transfer unchanged:

- scheduler_schedule_attempts_total{result,profile}
- scheduler_scheduling_attempt_duration_seconds{result,profile}
- scheduler_e2e_scheduling_duration_seconds{result,profile} (legacy name)
- scheduler_pending_pods{queue}
- scheduler_queue_incoming_pods_total{queue,event}
- scheduler_preemption_attempts_total
- scheduler_preemption_victims (histogram)
- scheduler_binding_duration_seconds
- scheduler_framework_extension_point_duration_seconds{extension_point,status}
- scheduler_plugin_execution_duration_seconds{plugin,extension_point,status}
- scheduler_pod_scheduling_attempts (histogram)
- scheduler_cache_size{type}

Batched-cycle additions (no upstream equivalent — the TPU design schedules
the whole pending set per cycle):

- scheduler_cycle_duration_seconds{phase} — encode / dispatch / device /
  decision_fetch / postfilter / diag_lag / apply / total (dispatch,
  decision_fetch and diag_lag are the split-phase serving-pipeline
  stages: async program dispatch, the slimmed blocking decision
  transfer, and how far FailedScheduling attribution trails the binds)
- scheduler_cycle_pods (histogram) — pending-set size per cycle
- scheduler_pod_node_decisions_total — P*N decisions evaluated (the
  north-star throughput numerator)
- scheduler_decision_fetch_bytes_total — bytes moved device->host by the
  blocking decision fetch (the slimmed payload; core/pipeline.py)
- scheduler_unschedulable_reasons_total{plugin,profile} — unschedulable
  attempts by first-rejecting plugin
- scheduler_program_retry_strikes_total{program,kind} — compiled-program
  retries absorbed by the resilience wrapper (core/cycle.py _Resilient)

Flight-recorder derived gauges (core/flight_recorder.py): continuous
pipeline-health signals computed from the cycle ring each cycle, so the
overlap story needs no probe runs:

- scheduler_pipeline_overlap_ratio — fraction of host encode time hidden
  behind in-flight device work over the recent cycle window (0 = fully
  serial, e.g. forcedSync; 1 = encode fully hidden)
- scheduler_cycle_inflight — dispatched-but-unfetched pipeline cycles
  right now (0 or 1 per pipeline under the ordering guard)
- scheduler_diag_lag_seconds — summary of how far the deferred
  FailedScheduling attribution trailed each cycle's decision fetch
- scheduler_last_cycle_age_seconds — seconds since the last completed
  cycle record (the /healthz staleness signal)

Latency-attribution / anomaly / SLO families (core/observe.py — the
streaming consumer of every flight record):

- scheduler_cycle_phase_seconds{phase} — streaming per-phase latency
  attribution of every committed cycle record; phases: total, encode,
  fold, encode_ingest, encode_finalize, dispatch, device,
  decision_fetch, bind, postfilter, diag_lag,
  compile, batch_wait, device_share, first_bind, submit_bind
  (encode_ingest / encode_finalize are the admission-time incremental
  encode split: the per-group ingest cost paid in the ack path's
  shadow, and the flush-time finalize residue; batch_wait and
  device_share are the multi-cycle batched decomposition: an inner
  cycle's host-side coalescing wait and its apportioned share of the
  batch's device window; first_bind is the streamed-fetch window from
  batch flush to the FIRST inner cycle's decisions landing — the
  latency a row-0 pod actually waits before its bind; submit_bind is
  the front door's end-to-end window from admission accept to the
  pod's bind, stamped per cycle as the worst such latency among that
  cycle's binds; the inventory is
  core/observe.PHASES, machine-checked by schedlint ID005 against the
  trace lane mapping and the README)
- scheduler_cycle_phase_p50_seconds{phase} /
  scheduler_cycle_phase_p99_seconds{phase} — per-phase quantiles from
  the observer's streaming histograms, evaluated at scrape time
- scheduler_anomalies_total{class} — typed anomaly detections
  (tunnel_stall | fetch_stall | recompile | fold_miss |
  wedge_precursor | degraded | speculation_thrash); each increment has
  a matching structured event in /debug/anomalies carrying the cycle
  seq
- scheduler_slo_burn_rate{window} — latency-SLO burn rate over the
  fast/slow cycle windows (1.0 = burning the error budget exactly at
  the sustainable rate), 0 when no sloP99Ms objective is configured
- scheduler_slo_budget_remaining — fraction of the slow window's
  violation budget left (1.0 = untouched, negative = overspent)

Multi-cycle serving families (core/scheduler.py _schedule_profile_multi
— K scheduling cycles per device dispatch, amortizing the dispatch
round trip):

- scheduler_multicycle_batch_cycles — inner scheduling cycles per
  multi-cycle device dispatch (1 = a degenerate single-cycle batch)
- scheduler_multicycle_inner_cycles_total — scheduling cycles served
  through multi-cycle dispatches (vs one dispatch per cycle)
- scheduler_speculation_total{outcome} — depth-2 speculative dispatch
  outcomes (adopted | abandoned | redispatched): a batch dispatched
  against the predicted post-predecessor carry is adopted when the
  host fold matches the speculation's predicate digest (zero added
  latency), abandoned on a mismatch, and its groups then re-dispatched
  against the true carry — correctness is never speculative
- scheduler_encode_ingest_seconds — admission-time incremental encode:
  per-group cost of parsing acked pods into staged row data in the ack
  path's shadow (work moved OFF the flush critical path)
- scheduler_encode_finalize_seconds — flush-time residue of the
  incremental encode: folding staged rows into the packed arena when
  the multi-cycle buffer flushes (what is left of the old O(P) rebuild)

Multi-chip serving families (shardDevices + parallel/audit.py — the
sharded carry path with shard-invariant tie-breaking):

- scheduler_shard_devices — devices the serving mesh shards the
  device-resident carry over (1 = single-device serving)
- scheduler_collective_payload_bytes{profile} — per-cycle cross-device
  collective payload of the profile's compiled cycle program, probed
  from its HLO at AOT-install time (the audit-gate parser; also
  stamped on every flight record and shown in /debug/state)

Compile-regime management families (core/compile_cache.py — persistent
AOT-executable cache + speculative pre-compilation):

- scheduler_compile_cache_hits_total — programs loaded from the
  persistent executable cache instead of compiling cold
- scheduler_compile_cache_misses_total — programs that compiled cold
  with the cache enabled (entry absent, corrupt, or
  fingerprint-mismatched; the fresh build is stored back)
- scheduler_compile_cache_loads_seconds — time to trace + deserialize a
  cached executable (vs the 8.8-16.8 s cold compile it replaces)
- scheduler_compile_cache_speculative_builds_total — adjacent pad
  regimes pre-built by the warm thread before churn crossed a bucket
  boundary (a flip speculation won costs ~0 serve-path compile)

Robustness / degradation families (core/degrade.py ladder +
core/pipeline.py dispatch watchdog + fetch-failure attribution):

- scheduler_degradation_rung — current degradation-ladder rung
  (0 = normal, 1 = retrace, 2 = sequential, 3 = forced_sync,
  4 = stateless); stepped down on dispatch failures, promoted back up
  after degradePromoteCycles clean cycles
- scheduler_degradation_transitions_total{from,to} — ladder rung
  transitions by from/to rung name (both directions; each has a
  matching events-ring entry and a `degraded` anomaly in
  /debug/anomalies)
- scheduler_fetch_failures_total{class} — consumed cycles whose
  blocking decision fetch raised, by failure class (transport |
  corrupt | wedge | deadline | other — the `_Resilient` marker
  classifiers plus the watchdog's deadline)

Submission front-door families (service/admission.py — the
admission-controlled Submit/NodeChurn RPCs and the open-loop load
harness that drives them):

- scheduler_admission_total{outcome} — submitted pods by admission
  outcome (accepted | shed | invalid); shed = backpressure
  (RESOURCE_EXHAUSTED + retry-after) from a full admission queue, an
  SLO fast-burn, or a degraded ladder rung — never silent loss
- scheduler_admission_queue_depth — admission queue depth (pending
  pods across all queue tiers plus pods coalescing in the multi-cycle
  buffers) as of the last submit or cycle
- scheduler_submit_ack_seconds — submit-to-ack latency of ACCEPTED
  submissions, including the WAL-before-ack group-fsync barrier (the
  durability contract's cost, paid off the scheduling hot path)

Multi-tenant arena families (tenancy/ package — virtual-cluster
lifecycle, per-tenant admission, and the batched arena dispatch):

- scheduler_tenancy_events_total{event} — tenant-lifecycle and
  per-tenant admission events (created | suspended | resumed |
  deleted | quota_shed | fair_shed | starved); labels stay
  event-typed, never tenant-id-typed, so a 1000-tenant fleet does not
  explode the registry cardinality
- scheduler_tenant_arena_dispatches_total — arena programs launched
  (one per (pad-regime bucket, tenant-count bucket) per fleet cycle);
  with scheduler_tenant_arena_tenants this gives tenants-per-dispatch,
  the batching amortization the 1000-tenant headline bench gates
- scheduler_tenant_arena_tenants — histogram of real (non-pad)
  tenants packed per arena dispatch

Tracing / build-identity families (core/spans.py span recorder +
cmd/main.py startup stamp):

- scheduler_trace_spans_total{name} — pod-lifecycle trace spans
  recorded, by span name (submit.validate | submit.journal |
  ack.barrier | mc.buffer_wait | encode.ingest | flush.finalize |
  dispatch | dispatch.speculative | decision.row | apply.fold |
  bind.confirm | preempt.victim; the inventory is
  core/spans.SPAN_NAMES, machine-checked by schedlint ID010 against
  this docstring and the README span table); spans serve at
  /debug/traces and join /debug/explain verdicts
- scheduler_build_info{python,jax,jaxlib,backend,git} — constant 1
  gauge carrying the process's build/runtime fingerprint as labels,
  set once at startup so dashboards can correlate latency shifts with
  binary or runtime changes; bench headline artifacts carry the same
  stamp (build_fingerprint())
- scheduler_uptime_seconds — seconds since SchedulerMetrics
  construction (process start for the CLI), evaluated at scrape time;
  joins build_info so restart storms are visible without log access
- scheduler_alerts_total{rule,severity} — declarative alert-rule
  firings from the in-process watchtower (metrics/rules.py; one
  increment per ok->firing transition, never per evaluation); the
  rule inventory is rules.BUILTIN_RULES, machine-checked by schedlint
  ID011 against the README alert table, and each firing also raises
  an `alert` anomaly and an AlertFiring event

Durable-state families (state/ package — write-ahead journal, snapshots,
restore) and leader election:

- scheduler_journal_appends_total{op} — journal records appended, by
  logical operation (q.add, q.pop, c.assume, ...)
- scheduler_journal_bytes_total — encoded journal bytes written to disk
- scheduler_journal_fsync_seconds — group-commit fsync latency (one
  fsync per drained batch, writer thread only — never the bind path)
- scheduler_journal_buffer_depth — records appended but not yet durable
  (the journal lag; grows if the disk can't keep up)
- scheduler_journal_segments — journal segment files on disk
- scheduler_snapshot_writes_total — snapshot compactions written
- scheduler_snapshot_duration_seconds — dump+write+prune latency
- scheduler_snapshot_last_bytes — size of the newest snapshot
- scheduler_snapshot_last_restore_records — journal records replayed by
  the most recent restore (0 after a clean-shutdown takeover)
- scheduler_snapshot_last_restore_seconds — how long that restore took
- scheduler_leader_state — 1 = this process holds the leader lease
  (or runs without election), 0 = standby (evaluated at scrape)
- scheduler_leader_lease_age_seconds — age of the lease heartbeat as
  this process observes it (standbys watch this to detect a dead
  active; dashboards see failovers)

Each `SchedulerMetrics` owns its own `CollectorRegistry`;
`global_metrics()` returns the process-wide default instance, which is
also what a Scheduler constructed without an explicit `metrics=` serves
on /metrics (process-level counters like
scheduler_program_retry_strikes_total land there). Tests or
multi-scheduler processes that need isolated registries pass their own
`SchedulerMetrics`.
"""

from __future__ import annotations

import threading
import time as _time

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    Summary,
    generate_latest,
)

# Buckets tuned for a <10ms-per-cycle target (BASELINE.md north star):
# upstream uses exponential 1ms..~16s; extend downward for TPU cycles.
_DURATION_BUCKETS = (
    0.0005, 0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)
_ATTEMPTS_BUCKETS = (1, 2, 3, 5, 8, 13, 21)
_VICTIM_BUCKETS = (1, 2, 4, 8, 16, 32, 64)
_PODS_BUCKETS = (1, 8, 64, 256, 1024, 4096, 16384, 65536)

RESULT_SCHEDULED = "scheduled"
RESULT_UNSCHEDULABLE = "unschedulable"
RESULT_ERROR = "error"


class SchedulerMetrics:
    def __init__(self, registry: CollectorRegistry | None = None) -> None:
        self.registry = registry or CollectorRegistry()
        r = self.registry
        self.schedule_attempts = Counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by result.",
            ["result", "profile"],
            registry=r,
        )
        self.attempt_duration = Histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (scheduling algorithm + binding).",
            ["result", "profile"],
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.e2e_duration = Histogram(
            "scheduler_e2e_scheduling_duration_seconds",
            "E2e scheduling latency (legacy name kept for dashboards).",
            ["result", "profile"],
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.pending_pods = Gauge(
            "scheduler_pending_pods",
            "Pending pods, by queue (active|backoff|unschedulable).",
            ["queue"],
            registry=r,
        )
        self.queue_incoming = Counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to scheduling queues by queue and event.",
            ["queue", "event"],
            registry=r,
        )
        self.preemption_attempts = Counter(
            "scheduler_preemption_attempts_total",
            "Total preemption attempts in the cluster so far.",
            registry=r,
        )
        self.preemption_victims = Histogram(
            "scheduler_preemption_victims",
            "Number of selected preemption victims.",
            buckets=_VICTIM_BUCKETS,
            registry=r,
        )
        self.binding_duration = Histogram(
            "scheduler_binding_duration_seconds",
            "Binding latency.",
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.extension_point_duration = Histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency for running all plugins of a specific extension point.",
            ["extension_point", "status"],
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.plugin_duration = Histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Duration for running a plugin at a specific extension point.",
            ["plugin", "extension_point", "status"],
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.pod_scheduling_attempts = Histogram(
            "scheduler_pod_scheduling_attempts",
            "Number of attempts to successfully schedule a pod.",
            buckets=_ATTEMPTS_BUCKETS,
            registry=r,
        )
        self.cache_size = Gauge(
            "scheduler_cache_size",
            "Scheduler cache size, by type (nodes|pods|assumed_pods).",
            ["type"],
            registry=r,
        )
        # ---- batched-cycle additions ----
        self.cycle_duration = Histogram(
            "scheduler_cycle_duration_seconds",
            "Batched scheduling cycle latency by phase (encode|dispatch|"
            "device|decision_fetch|postfilter|diag_lag|apply|total).",
            ["phase"],
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.cycle_pods = Histogram(
            "scheduler_cycle_pods",
            "Pending-set size per batched cycle.",
            buckets=_PODS_BUCKETS,
            registry=r,
        )
        self.decisions = Counter(
            "scheduler_pod_node_decisions_total",
            "Pod-node feasibility+scoring decisions evaluated (P*N per "
            "cycle) — the north-star throughput numerator.",
            registry=r,
        )
        self.unschedulable_reasons = Counter(
            "scheduler_unschedulable_reasons_total",
            "Unschedulable attempts by first-rejecting plugin (per-pod "
            "failure attribution from the batched cycle).",
            ["plugin", "profile"],
            registry=r,
        )
        self.decision_fetch_bytes = Counter(
            "scheduler_decision_fetch_bytes_total",
            "Bytes moved device->host by the blocking per-cycle decision "
            "fetch (slimmed payload: i16 assignment + u8 flags per pod).",
            registry=r,
        )
        # ---- admission-time incremental encode (models/encoding.py) ----
        self.encode_ingest = Histogram(
            "scheduler_encode_ingest_seconds",
            "Admission-time incremental encode: per-group cost of parsing "
            "acked pods into staged row data in the ack path's shadow "
            "(work moved off the flush critical path).",
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.encode_finalize = Histogram(
            "scheduler_encode_finalize_seconds",
            "Flush-time residue of the incremental encode: folding staged "
            "rows into the packed arena at multi-cycle flush (what is "
            "left of the old O(P) rebuild).",
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        # ---- flight-recorder derived gauges (core/flight_recorder.py) ----
        self.pipeline_overlap = Gauge(
            "scheduler_pipeline_overlap_ratio",
            "Fraction of host encode time hidden behind in-flight device "
            "work over the recent flight-recorder window (0 = serial).",
            registry=r,
        )
        self.cycle_inflight = Gauge(
            "scheduler_cycle_inflight",
            "Dispatched-but-unfetched serving-pipeline cycles right now.",
            registry=r,
        )
        self.diag_lag = Summary(
            "scheduler_diag_lag_seconds",
            "How far the deferred FailedScheduling attribution trailed "
            "the cycle's blocking decision fetch.",
            registry=r,
        )
        self.last_cycle_age = Gauge(
            "scheduler_last_cycle_age_seconds",
            "Seconds since the last completed scheduling cycle record "
            "(the /healthz staleness signal).",
            registry=r,
        )
        # ---- latency attribution / anomalies / SLO (core/observe.py) ----
        # same edge family as observe.PHASE_BUCKETS_S (kept literal here
        # so this module stays importable without the core package):
        # sub-ms TPU phases up through multi-second tunnel stalls
        phase_buckets = (
            0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
            0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
        )
        self.cycle_phase = Histogram(
            "scheduler_cycle_phase_seconds",
            "Per-phase latency attribution of every committed cycle "
            "record (phases: total, encode, fold, dispatch, device, "
            "decision_fetch, bind, postfilter, diag_lag, compile).",
            ["phase"],
            buckets=phase_buckets,
            registry=r,
        )
        self.cycle_phase_p50 = Gauge(
            "scheduler_cycle_phase_p50_seconds",
            "Streaming per-phase p50 from the cycle observer, evaluated "
            "at scrape time.",
            ["phase"],
            registry=r,
        )
        self.cycle_phase_p99 = Gauge(
            "scheduler_cycle_phase_p99_seconds",
            "Streaming per-phase p99 from the cycle observer, evaluated "
            "at scrape time.",
            ["phase"],
            registry=r,
        )
        self.anomalies = Counter(
            "scheduler_anomalies_total",
            "Typed anomaly detections from the cycle observer "
            "(tunnel_stall | fetch_stall | recompile | fold_miss | "
            "wedge_precursor | degraded | speculation_thrash); each "
            "has a structured /debug/anomalies event carrying the "
            "cycle seq.",
            ["class"],
            registry=r,
        )
        self.slo_burn_rate = Gauge(
            "scheduler_slo_burn_rate",
            "Latency-SLO burn rate over the fast/slow cycle windows "
            "(1.0 = burning budget at exactly the sustainable rate).",
            ["window"],
            registry=r,
        )
        self.slo_budget_remaining = Gauge(
            "scheduler_slo_budget_remaining",
            "Fraction of the slow-window SLO violation budget left "
            "(1.0 = untouched, negative = overspent).",
            registry=r,
        )
        # ---- multi-cycle serving (core/scheduler.py) ----
        self.multicycle_batch = Histogram(
            "scheduler_multicycle_batch_cycles",
            "Inner scheduling cycles per multi-cycle device dispatch "
            "(multiCycleK coalescing; 1 = a degenerate batch).",
            buckets=(1, 2, 4, 8, 16, 32),
            registry=r,
        )
        self.multicycle_cycles = Counter(
            "scheduler_multicycle_inner_cycles_total",
            "Scheduling cycles served through multi-cycle dispatches "
            "(each paid dispatch_rt/K instead of a full round trip).",
            registry=r,
        )
        self.speculation = Counter(
            "scheduler_speculation_total",
            "Depth-2 speculative dispatch outcomes (adopted | abandoned"
            " | redispatched): batches dispatched against the predicted"
            " post-predecessor carry while it was still on device.",
            ["outcome"],
            registry=r,
        )
        # ---- multi-chip serving (ops/argsel.py + parallel/) ----
        self.shard_devices = Gauge(
            "scheduler_shard_devices",
            "Devices the serving mesh shards the device-resident carry "
            "over (1 = single-device; placements are bit-identical at "
            "any count — the shard-invariant tie-break contract).",
            registry=r,
        )
        self.collective_payload = Gauge(
            "scheduler_collective_payload_bytes",
            "Per-cycle cross-device collective payload of the current "
            "regime's compiled cycle program, probed from its HLO at "
            "AOT-install time (parallel/audit.py; 0 = no AOT probe).",
            ["profile"],
            registry=r,
        )
        # ---- compile-regime management (core/compile_cache.py) ----
        self.compile_cache_hits = Counter(
            "scheduler_compile_cache_hits_total",
            "Programs loaded from the persistent executable cache "
            "instead of compiling cold.",
            registry=r,
        )
        self.compile_cache_misses = Counter(
            "scheduler_compile_cache_misses_total",
            "Programs that compiled cold with the cache enabled (entry "
            "absent, corrupt, or fingerprint-mismatched).",
            registry=r,
        )
        self.compile_cache_loads = Histogram(
            "scheduler_compile_cache_loads_seconds",
            "Time to trace + deserialize a cached executable (replaces "
            "a multi-second cold compile).",
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.compile_cache_speculative = Counter(
            "scheduler_compile_cache_speculative_builds_total",
            "Adjacent pad regimes pre-built by the speculative warm "
            "thread before churn crossed a bucket boundary.",
            registry=r,
        )
        # ---- robustness / degradation (core/degrade.py) ----
        self.degradation_rung = Gauge(
            "scheduler_degradation_rung",
            "Current degradation-ladder rung (0 = normal, 1 = retrace, "
            "2 = sequential, 3 = forced_sync, 4 = stateless).",
            registry=r,
        )
        self.degradation_transitions = Counter(
            "scheduler_degradation_transitions_total",
            "Degradation-ladder rung transitions by from/to rung name "
            "(both directions; each has an events-ring entry and a "
            "'degraded' anomaly).",
            ["from", "to"],
            registry=r,
        )
        self.fetch_failures = Counter(
            "scheduler_fetch_failures_total",
            "Consumed cycles whose blocking decision fetch raised, by "
            "failure class (transport | corrupt | wedge | deadline | "
            "other).",
            ["class"],
            registry=r,
        )
        # ---- submission front door (service/admission.py) ----
        self.admission_total = Counter(
            "scheduler_admission_total",
            "Submitted pods by admission outcome (accepted | shed | "
            "invalid); shed = explicit backpressure, never silent loss.",
            ["outcome"],
            registry=r,
        )
        self.admission_queue_depth = Gauge(
            "scheduler_admission_queue_depth",
            "Admission queue depth (pending pods across all queue "
            "tiers + pods coalescing in the multi-cycle buffers) as of "
            "the last submit or cycle.",
            registry=r,
        )
        self.submit_ack = Histogram(
            "scheduler_submit_ack_seconds",
            "Submit-to-ack latency of accepted submissions, including "
            "the WAL-before-ack group-fsync barrier.",
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        # ---- multi-tenant arena (tenancy/) ----
        self.tenancy_events = Counter(
            "scheduler_tenancy_events_total",
            "Tenant-lifecycle and per-tenant admission events "
            "(created | suspended | resumed | deleted | quota_shed | "
            "fair_shed | starved); event-typed labels only, never "
            "per-tenant ids.",
            ["event"],
            registry=r,
        )
        self.arena_dispatches = Counter(
            "scheduler_tenant_arena_dispatches_total",
            "Arena programs launched (one per pad-regime/tenant-count "
            "bucket per fleet cycle).",
            registry=r,
        )
        self.arena_tenants = Histogram(
            "scheduler_tenant_arena_tenants",
            "Real (non-pad) tenants packed per arena dispatch.",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
            registry=r,
        )
        # ---- pod-lifecycle tracing / build identity (core/spans.py) ----
        self.trace_spans = Counter(
            "scheduler_trace_spans_total",
            "Pod-lifecycle trace spans recorded, by span name (the "
            "core/spans.SPAN_NAMES inventory; serves /debug/traces).",
            ["name"],
            registry=r,
        )
        self.build_info = Gauge(
            "scheduler_build_info",
            "Constant 1 gauge carrying the build/runtime fingerprint "
            "as labels (python | jax | jaxlib | backend | git), set "
            "once at startup (build_fingerprint()).",
            ["python", "jax", "jaxlib", "backend", "git"],
            registry=r,
        )
        self.uptime = Gauge(
            "scheduler_uptime_seconds",
            "Seconds since SchedulerMetrics construction (process "
            "start for the CLI), evaluated at scrape time.",
            registry=r,
        )
        _t0 = _time.monotonic()
        # whole seconds: sub-second precision is useless for an uptime
        # join, and a full-precision float would make the rendered
        # /metrics payload length differ between back-to-back scrapes
        # (GET vs HEAD Content-Length must agree)
        self.uptime.set_function(
            lambda: float(int(_time.monotonic() - _t0))
        )
        self.alerts = Counter(
            "scheduler_alerts_total",
            "Watchtower alert-rule firings by rule name and severity "
            "(metrics/rules.py; one increment per ok->firing "
            "transition).",
            ["rule", "severity"],
            registry=r,
        )
        # ---- durable state (state/: journal + snapshots + restore) ----
        self.journal_appends = Counter(
            "scheduler_journal_appends_total",
            "Write-ahead-journal records appended, by logical op.",
            ["op"],
            registry=r,
        )
        self.journal_bytes = Counter(
            "scheduler_journal_bytes_total",
            "Encoded journal bytes written to segment files.",
            registry=r,
        )
        self.journal_fsync = Histogram(
            "scheduler_journal_fsync_seconds",
            "Group-commit fsync latency (one fsync per drained batch, "
            "issued only by the journal writer thread).",
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.journal_buffer = Gauge(
            "scheduler_journal_buffer_depth",
            "Journal records appended but not yet durable (journal lag).",
            registry=r,
        )
        self.journal_segments = Gauge(
            "scheduler_journal_segments",
            "Journal segment files currently on disk.",
            registry=r,
        )
        self.snapshot_writes = Counter(
            "scheduler_snapshot_writes_total",
            "Snapshot compactions written durably.",
            registry=r,
        )
        self.snapshot_duration = Histogram(
            "scheduler_snapshot_duration_seconds",
            "Snapshot dump+write+prune latency.",
            buckets=_DURATION_BUCKETS,
            registry=r,
        )
        self.snapshot_bytes = Gauge(
            "scheduler_snapshot_last_bytes",
            "Size of the newest durable snapshot.",
            registry=r,
        )
        self.restore_records = Gauge(
            "scheduler_snapshot_last_restore_records",
            "Journal records replayed by the most recent restore "
            "(0 after a clean-shutdown takeover).",
            registry=r,
        )
        self.restore_duration = Gauge(
            "scheduler_snapshot_last_restore_seconds",
            "Duration of the most recent snapshot+tail restore.",
            registry=r,
        )
        # ---- leader election (cmd/leaderelection.py FileLease) ----
        self.leader_state = Gauge(
            "scheduler_leader_state",
            "1 = this process holds the leader lease (or runs without "
            "election), 0 = standby. Evaluated at scrape time.",
            registry=r,
        )
        self.leader_lease_age = Gauge(
            "scheduler_leader_lease_age_seconds",
            "Age of the lease heartbeat as observed by this process "
            "(grows past leaseDuration when the active is dead).",
            registry=r,
        )
        self.program_retry_strikes = Counter(
            "scheduler_program_retry_strikes_total",
            "Compiled-program retries absorbed by the resilience wrapper "
            "(kind=executable_cache pays clear_cache+retrace in-cycle; "
            "kind=transport pays a backoff re-invoke).",
            ["program", "kind"],
            registry=r,
        )

    # ---- convenience recorders ------------------------------------------

    def observe_attempt(
        self, result: str, seconds: float, profile: str = "default-scheduler"
    ) -> None:
        self.schedule_attempts.labels(result=result, profile=profile).inc()
        self.attempt_duration.labels(result=result, profile=profile).observe(
            seconds
        )
        self.e2e_duration.labels(result=result, profile=profile).observe(
            seconds
        )

    @staticmethod
    def _observe_n(hist_child, value: float, n: int) -> bool:
        """Record `n` identical samples on a Histogram child in O(1).

        prometheus_client stores per-bucket counts non-cumulatively and
        accumulates at exposition, so n samples of the same value are
        exactly: sum += value*n, first-bucket-with-bound>=value += n.
        Pokes client internals (_sum/_upper_bounds/_buckets); returns
        False untouched if the layout ever changes, and the caller
        falls back to n scalar observes.
        """
        try:
            s = hist_child._sum
            bounds = hist_child._upper_bounds
            buckets = hist_child._buckets
        except AttributeError:
            return False
        s.inc(value * n)
        for i, bound in enumerate(bounds):
            if value <= bound:
                buckets[i].inc(n)
                break
        return True

    def observe_attempts(
        self,
        result: str,
        seconds: float,
        profile: str = "default-scheduler",
        n: int = 1,
    ) -> None:
        """Batched observe_attempt: n attempts sharing one outcome and
        one latency sample, recorded with O(1) metric mutations per
        cycle instead of O(n) — the apply-fold's per-pod metric cost
        collapses to a constant."""
        if n <= 0:
            return
        self.schedule_attempts.labels(result=result, profile=profile).inc(n)
        for h in (self.attempt_duration, self.e2e_duration):
            child = h.labels(result=result, profile=profile)
            if not self._observe_n(child, seconds, n):
                for _ in range(n):
                    child.observe(seconds)

    def set_pending(self, counts: dict[str, int]) -> None:
        for queue, n in counts.items():
            self.pending_pods.labels(queue=queue).set(n)

    def set_cache(self, nodes: int, pods: int, assumed: int) -> None:
        self.cache_size.labels(type="nodes").set(nodes)
        self.cache_size.labels(type="pods").set(pods)
        self.cache_size.labels(type="assumed_pods").set(assumed)

    def set_build_info(self, info: dict[str, str] | None = None) -> None:
        """Stamp scheduler_build_info once from a build_fingerprint()
        dict (computed fresh when omitted)."""
        self.build_info.labels(**(info or build_fingerprint())).set(1)

    def expose(self) -> bytes:
        """Prometheus text exposition (the /metrics payload)."""
        return generate_latest(self.registry)


def build_fingerprint() -> dict[str, str]:
    """Best-effort build/runtime identity for scheduler_build_info and
    bench headline stamps: python/jax/jaxlib versions, the JAX backend
    actually serving cycles, and `git describe` of the working tree.
    Every probe degrades to a placeholder — this must never fail in a
    wheel install without git or on a box without jax.
    """
    import platform

    info = {
        "python": platform.python_version(),
        "jax": "unavailable",
        "jaxlib": "unavailable",
        "backend": "unavailable",
        "git": "unknown",
    }
    try:  # schedlint: disable=RB001 -- identity probe, never load-bearing
        import jax

        info["jax"] = str(getattr(jax, "__version__", "unknown"))
        info["backend"] = str(jax.default_backend())
    except Exception:  # schedlint: disable=RB001 -- jax optional here
        pass
    try:  # schedlint: disable=RB001 -- identity probe, never load-bearing
        import jaxlib

        info["jaxlib"] = str(getattr(jaxlib, "__version__", "unknown"))
    except Exception:  # schedlint: disable=RB001 -- jaxlib optional here
        pass
    try:  # schedlint: disable=RB001 -- identity probe, never load-bearing
        import os
        import subprocess

        out = subprocess.run(
            ["git", "describe", "--always", "--dirty", "--tags"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True,
            text=True,
            timeout=5.0,
        )
        if out.returncode == 0 and out.stdout.strip():
            info["git"] = out.stdout.strip()
    except Exception:  # schedlint: disable=RB001 -- git optional here
        pass
    return info


_global_lock = threading.Lock()
_global: SchedulerMetrics | None = None


def global_metrics() -> SchedulerMetrics:
    global _global
    with _global_lock:
        if _global is None:
            _global = SchedulerMetrics()
        return _global
