"""Declarative recording + alert rules over the in-process TSDB.

A `Rule` is a structured object — family + label selector, windowed
aggregation, comparator, threshold, `for`-duration, severity,
clear-hysteresis — and the `RuleEngine` evaluates the pack against
`metrics/tsdb.py` history each cycle (throttled) and from the wall
ticker, so alerts keep evaluating even when the scheduling loop is
wedged. A firing rule:

- lands in the events ring (`AlertFiring` / `AlertResolved`
  scheduler-level events, core/events.py),
- raises an `alert` anomaly (core/observe.py ANOMALY_CLASSES) carrying
  rule name, severity, observed value and threshold,
- increments `scheduler_alerts_total{rule,severity}`,
- shows in `/debug/alerts` as active until it resolves, then in the
  resolved tail with both wall timestamps.

State machine per rule: ok -> pending (condition true) -> firing
(condition held for `for_s`) -> resolved (condition false AGAINST THE
CLEAR THRESHOLD for `for_s` — hysteresis on both the value axis via
`clear` and the time axis via the symmetric hold, so a value oscillating
around the threshold cannot flap the alert).

`BUILTIN_RULES` is the committed rule pack. It is a module-level
literal on purpose: schedlint's ID011 check AST-parses it and pins the
rule names against the README alert table and the `alert` anomaly-class
docs, the same machine-checked-inventory discipline as the metric and
phase tables. Operators extend the pack with `alertRulesFile`
(YAML/JSON list of the same shape).

Rules with `"kind": "record"` are recording rules: the aggregated value
is appended back into the TSDB under `record_as` each evaluation,
giving derived series (e.g. a smoothed anomaly rate) their own history
and making them selectable by other rules and the dashboard.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import time
from typing import Iterable

log = logging.getLogger(__name__)

# Event-ring reasons for rule transitions; mirrored as constants in
# core/events.py (kept as literals here so metrics/ stays importable
# without the core package).
ALERT_FIRING = "AlertFiring"
ALERT_RESOLVED = "AlertResolved"

SEVERITIES = ("critical", "warning", "info")
AGGS = ("avg", "min", "max", "sum", "last", "rate", "count")
OPS = (">", ">=", "<", "<=")

# The committed built-in rule pack. Thresholds are production-shaped
# (windows in wall seconds); tests scale them down via `scale_rules`.
# Pinned by schedlint ID011: every "name" below must appear in the
# README Observability alert table, and the `alert` anomaly class these
# firings raise must stay documented in core/observe.ANOMALY_CLASSES.
BUILTIN_RULES = (
    # SLO fast-window burn: spending error budget > 6x sustainable.
    {"name": "slo_fast_burn", "family": "scheduler_slo_burn_rate",
     "labels": {"window": "fast"}, "agg": "avg", "window_s": 30.0,
     "op": ">", "threshold": 6.0, "for_s": 15.0, "clear": 2.0,
     "severity": "critical"},
    # Degradation ladder sitting below normal (rung > 0).
    {"name": "degraded_rung", "family": "scheduler_degradation_rung",
     "labels": {}, "agg": "last", "window_s": 60.0,
     "op": ">", "threshold": 0.5, "for_s": 10.0,
     "severity": "warning"},
    # A tenant repeatedly losing every arena auction it entered.
    {"name": "tenant_starved_streak",
     "family": "scheduler_anomalies_total",
     "labels": {"class": "tenant_starved"}, "agg": "rate",
     "window_s": 60.0, "op": ">", "threshold": 0.03, "for_s": 30.0,
     "severity": "warning"},
    # Aggregate anomaly rate across every class.
    {"name": "anomaly_rate", "family": "scheduler_anomalies_total",
     "labels": {}, "agg": "rate", "window_s": 60.0,
     "op": ">", "threshold": 1.0, "for_s": 15.0, "clear": 0.5,
     "severity": "warning"},
    # Tunnel round-trip stall burst (the FaultPlan fetch-stall shape).
    {"name": "tunnel_stall_burst", "family": "scheduler_anomalies_total",
     "labels": {"class": "tunnel_stall"}, "agg": "rate",
     "window_s": 30.0, "op": ">", "threshold": 0.2, "for_s": 10.0,
     "clear": 0.05, "severity": "critical"},
    # Journal records appended but not yet durable (fsync lag).
    {"name": "journal_buffer_depth",
     "family": "scheduler_journal_buffer_depth", "labels": {},
     "agg": "max", "window_s": 15.0, "op": ">", "threshold": 1024.0,
     "for_s": 10.0, "clear": 256.0, "severity": "warning"},
    # Executable-cache misses on the serve path (cold compiles).
    {"name": "compile_cache_miss_spike",
     "family": "scheduler_compile_cache_misses_total", "labels": {},
     "agg": "rate", "window_s": 60.0, "op": ">", "threshold": 0.5,
     "for_s": 20.0, "severity": "warning"},
    # Consumed cycles whose blocking decision fetch raised.
    {"name": "fetch_failure_rate",
     "family": "scheduler_fetch_failures_total", "labels": {},
     "agg": "rate", "window_s": 60.0, "op": ">", "threshold": 0.2,
     "for_s": 20.0, "clear": 0.05, "severity": "critical"},
    # Front door shedding submissions (explicit backpressure).
    {"name": "admission_shed_rate", "family": "scheduler_admission_total",
     "labels": {"outcome": "shed"}, "agg": "rate", "window_s": 60.0,
     "op": ">", "threshold": 0.1, "for_s": 15.0,
     "severity": "warning"},
    # Recording rule: smoothed anomaly rate as its own series.
    {"name": "anomaly_rate_1m", "kind": "record",
     "family": "scheduler_anomalies_total", "labels": {},
     "agg": "rate", "window_s": 60.0,
     "record_as": "anomaly_rate_1m"},
)


@dataclasses.dataclass(frozen=True)
class Rule:
    """One declarative rule; `kind` is "alert" or "record"."""

    name: str
    family: str
    agg: str
    window_s: float
    labels: tuple = ()
    kind: str = "alert"
    op: str = ">"
    threshold: float = 0.0
    for_s: float = 0.0
    severity: str = "warning"
    clear: float | None = None  # hysteresis clear threshold
    record_as: str = ""

    @classmethod
    def from_dict(cls, d: dict) -> "Rule":
        d = dict(d)
        labels = tuple(sorted(
            (str(k), str(v)) for k, v in (d.pop("labels", {}) or {}).items()))
        rule = cls(labels=labels, **d)
        if not rule.name or not rule.family:
            raise ValueError(f"rule needs name and family: {d}")
        if rule.agg not in AGGS:
            raise ValueError(f"rule {rule.name}: bad agg {rule.agg!r}")
        if rule.kind == "alert":
            if rule.op not in OPS:
                raise ValueError(f"rule {rule.name}: bad op {rule.op!r}")
            if rule.severity not in SEVERITIES:
                raise ValueError(
                    f"rule {rule.name}: bad severity {rule.severity!r}")
        elif rule.kind == "record":
            if not rule.record_as:
                raise ValueError(f"rule {rule.name}: record needs record_as")
        else:
            raise ValueError(f"rule {rule.name}: bad kind {rule.kind!r}")
        return rule

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["labels"] = dict(self.labels)
        return d


def builtin_rules() -> list[Rule]:
    return [Rule.from_dict(d) for d in BUILTIN_RULES]


def scale_rules(rules: Iterable[Rule], time_scale: float) -> list[Rule]:
    """Scales window/for durations (tests and bench replay shrink the
    production windows instead of sleeping through them)."""
    return [dataclasses.replace(r, window_s=r.window_s * time_scale,
                                for_s=r.for_s * time_scale)
            for r in rules]


def load_rules_file(path: str) -> list[Rule]:
    """Loads operator rules (YAML or JSON list of rule dicts)."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except ValueError:
        import yaml  # same lazy-dep posture as config loading
        data = yaml.safe_load(text)
    if not isinstance(data, list):
        raise ValueError(f"{path}: expected a list of rule objects")
    return [Rule.from_dict(d) for d in data]


def _cmp(value: float, op: str, threshold: float) -> bool:
    if op == ">":
        return value > threshold
    if op == ">=":
        return value >= threshold
    if op == "<":
        return value < threshold
    return value <= threshold


class _State:
    __slots__ = ("stage", "since", "clear_since", "value", "record")

    def __init__(self):
        self.stage = "ok"  # ok | pending | firing
        self.since = 0.0
        self.clear_since = 0.0
        self.value: float | None = None
        self.record: dict | None = None


class RuleEngine:
    """Evaluates a rule pack against the TSDB; see module docstring.

    Driven by `MetricsTSDB.maybe_evaluate` (cycle observer + wall
    ticker, throttled + serialized there), so `evaluate` itself needs no
    internal locking beyond what the TSDB snapshot discipline gives."""

    def __init__(self, rules: Iterable[Rule], tsdb,
                 observer=None, events=None, metrics=None,
                 history: int = 256):
        self.rules = list(rules)
        self.tsdb = tsdb
        self.observer = observer
        self.events = events
        self.metrics = metrics
        self._states = {r.name: _State() for r in self.rules}
        self.history: collections.deque = collections.deque(maxlen=history)
        self.fired_total = 0
        self.evaluations = 0

    # ---- value extraction -------------------------------------------

    def _series_value(self, rule: Rule, points: list) -> tuple | None:
        """(value, weight) aggregate of one series' window, or None."""
        if not points:
            return None
        # raw rows are [t, v]; bucket rows are [t, min, max, sum, count,
        # last] — normalize to per-point stats
        if len(points[0]) == 2:
            vals = [p[1] for p in points]
            mn, mx, sm, cnt, last = (min(vals), max(vals), sum(vals),
                                     len(vals), vals[-1])
            first_t, first_v = points[0][0], points[0][1]
            last_t, last_v = points[-1][0], points[-1][1]
        else:
            mn = min(p[1] for p in points)
            mx = max(p[2] for p in points)
            sm = sum(p[3] for p in points)
            cnt = sum(p[4] for p in points)
            last = points[-1][5]
            first_t, first_v = points[0][0], points[0][5]
            last_t, last_v = points[-1][0], points[-1][5]
        if rule.agg == "rate":
            if last_t <= first_t:
                return None
            # counter rate; clamp at 0 so a counter reset reads as
            # quiet, not as a huge negative rate
            return (max(0.0, (last_v - first_v) / (last_t - first_t)), cnt)
        if rule.agg == "avg":
            return (sm / cnt, cnt) if cnt else None
        if rule.agg == "min":
            return (mn, cnt)
        if rule.agg == "max":
            return (mx, cnt)
        if rule.agg == "sum":
            return (sm, cnt)
        if rule.agg == "count":
            return (float(cnt), cnt)
        return (last, cnt)  # "last"

    def _value(self, rule: Rule, now: float) -> float | None:
        step = 0.0 if rule.window_s <= 600 else 1.0
        q = self.tsdb.query(rule.family, labels=dict(rule.labels),
                            window_s=rule.window_s, step_s=step, now=now)
        per = [self._series_value(rule, s["points"]) for s in q["series"]]
        per = [p for p in per if p is not None]
        if not per:
            return None
        if rule.agg in ("rate", "sum", "count"):
            return sum(v for v, _ in per)
        if rule.agg == "min":
            return min(v for v, _ in per)
        if rule.agg in ("max", "last"):
            return max(v for v, _ in per)
        total = sum(w for _, w in per)  # "avg": weight by sample count
        return (sum(v * w for v, w in per) / total) if total else None

    # ---- state machine ----------------------------------------------

    def evaluate(self, now: float | None = None) -> None:
        now = time.time() if now is None else now
        # serialized by MetricsTSDB.maybe_evaluate's _eval_lock (the
        # only concurrent callers — cycle observer + wall ticker — both
        # enter through it); direct calls are single-threaded test rigs
        self.evaluations += 1  # schedlint: disable=TR001 -- maybe_evaluate serializes every concurrent caller
        for rule in self.rules:
            st = self._states[rule.name]
            v = self._value(rule, now)
            st.value = v
            if rule.kind == "record":
                if v is not None:
                    self.tsdb.append(rule.record_as, (), v, t=now)
                continue
            cond = v is not None and _cmp(v, rule.op, rule.threshold)
            if st.stage == "ok":
                if cond:
                    st.stage, st.since = "pending", now
            elif st.stage == "pending" and not cond:
                st.stage = "ok"
            if st.stage == "pending" and now - st.since >= rule.for_s:
                self._fire(rule, st, now)
                continue
            if st.stage == "firing":
                clear_thr = (rule.threshold if rule.clear is None
                             else rule.clear)
                cleared = v is None or not _cmp(v, rule.op, clear_thr)
                if not cleared:
                    st.clear_since = 0.0
                elif st.clear_since == 0.0:
                    st.clear_since = now
                elif now - st.clear_since >= rule.for_s:
                    self._resolve(rule, st, now)

    def _fire(self, rule: Rule, st: _State, now: float) -> None:
        st.stage, st.since, st.clear_since = "firing", now, 0.0
        self.fired_total += 1  # schedlint: disable=TR001 -- only called from evaluate; maybe_evaluate serializes
        value = st.value if st.value is not None else 0.0
        st.record = {
            "rule": rule.name, "severity": rule.severity,
            "family": rule.family, "labels": dict(rule.labels),
            "value": value, "threshold": rule.threshold,
            "op": rule.op, "for_s": rule.for_s,
            "fired_wall": now, "resolved_wall": None,
        }
        self.history.append(st.record)
        msg = (f"alert {rule.name} firing [{rule.severity}]: "
               f"{rule.family} {rule.agg}/{rule.window_s:g}s = {value:.4g} "
               f"{rule.op} {rule.threshold:g} held {rule.for_s:g}s")
        log.warning("%s", msg)
        if self.events is not None:
            self.events.system(ALERT_FIRING, msg)
        if self.observer is not None:
            self.observer.raise_anomaly(
                "alert", value_s=float(value), rule=rule.name,
                severity=rule.severity, threshold=rule.threshold,
                family=rule.family)
        if self.metrics is not None:
            self.metrics.alerts.labels(
                rule=rule.name, severity=rule.severity).inc()

    def _resolve(self, rule: Rule, st: _State, now: float) -> None:
        st.stage, st.clear_since = "ok", 0.0
        if st.record is not None:
            st.record["resolved_wall"] = now
        msg = (f"alert {rule.name} resolved after "
               f"{now - (st.record or {}).get('fired_wall', now):.1f}s")
        log.info("%s", msg)
        if self.events is not None:
            self.events.system(ALERT_RESOLVED, msg)
        st.record = None

    # ---- read side ---------------------------------------------------

    def status(self) -> dict:
        """Payload for `/debug/alerts` and the black box."""
        active, rules = [], []
        for rule in self.rules:
            st = self._states[rule.name]
            rules.append({
                **rule.to_dict(), "state": st.stage, "value": st.value,
                "since": st.since or None,
            })
            if st.stage == "firing" and st.record is not None:
                active.append(dict(st.record))
        resolved = [dict(r) for r in self.history
                    if r.get("resolved_wall") is not None]
        return {
            "active": active,
            "resolved": resolved,
            "rules": rules,
            "fired_total": self.fired_total,
            "evaluations": self.evaluations,
        }


def replay_alerts(samples_s: Iterable[float],
                  rules: Iterable[Rule] | None = None) -> dict:
    """Replays a bench per-cycle latency series through the production
    classifier AND the built-in rule pack (mirror of
    core/observe.classify_latency_series): each cycle advances a
    virtual wall clock by one second — so a 60 s rule window reads as a
    60-cycle window — feeds the observer's cumulative anomaly counters
    into a throwaway TSDB, and evaluates the pack. Returns
    {"alerts_fired": n, "fired_rules": [...]} for the bench headline."""
    from ..core.observe import CycleObserver  # lazy: avoids cycles
    from .tsdb import MetricsTSDB

    tsdb = MetricsTSDB(raw_cap=256)
    engine = RuleEngine(rules if rules is not None else builtin_rules(),
                        tsdb)
    obs = CycleObserver(metrics=None)
    fired_rules: set[str] = set()
    for i, t in enumerate(samples_s):
        obs.observe_phases(
            {"total": t, "device": t, "decision_fetch": t},
            profile="bench", seq=i,
        )
        now = float(i + 1)
        for cls, n in obs.anomaly_counts.items():
            tsdb.append("scheduler_anomalies_total",
                        (("class", cls),), float(n), t=now)
        engine.evaluate(now)
        for rule in engine.rules:
            if engine._states[rule.name].stage == "firing":
                fired_rules.add(rule.name)
    return {"alerts_fired": engine.fired_total,
            "fired_rules": sorted(fired_rules)}
