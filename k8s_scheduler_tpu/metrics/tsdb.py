"""In-process metrics time-series store (the watchtower's memory).

Every `scheduler_*` family is scrape-time-only: `set_function` gauges
evaluate at `/metrics` GET and their history evaporates with the
response. Post-mortems need "what was burn rate doing in the 60 s
before the ladder dropped" WITHOUT an external Prometheus having
scraped at the right moment, so this module keeps a bounded history
inside the process, on the flight-recorder discipline: bounded rings,
seqlock-style publication, writers never block the serve loop.

Two samplers feed the store:

- `MetricsTSDB.observe_record` rides the existing
  `FlightRecorder.observers` publish hook: each committed cycle record
  contributes its phase durations (`cycle_phase_ms{phase}`)
  and integer counts (`cycle_count{key}`) at cycle rate.
- a low-frequency wall ticker (`start_ticker`) walks the Prometheus
  registry's `collect()` — which is exactly a scrape, so `set_function`
  gauges evaluate — and appends every family/labelset sample
  (histogram `_bucket`/`_created` series excluded to bound fan-out).

Storage is one `_Series` per (family, labelset): a raw ring of
`(t, value)` pairs plus tiered downsampling into 1 s and 1 m aggregate
buckets carrying `(bucket_t, min, max, sum, count, last)`. Append is
O(1) (ring slot store + two in-place bucket folds); memory is bounded
by `cap` knobs and a hard series-count ceiling, so a months-lived
daemon holds hours of 1 m history in a few MB.

Concurrency: two writer threads exist (the scheduling loop via the
observer hook, the wall ticker) and take a small lock ONLY against each
other — readers never take it. Slots and open buckets are immutable
tuples replaced wholesale, publication is a per-series monotonically
increasing `commits` counter, and readers retry their window copy until
no commit tore it (`core/flight_recorder.py` seqlock discipline).

Arming follows `core/spans.py`: module-level `ARMED` flag +
`arm()`/`disarm()`; unarmed, the observer hook is one global load and a
branch, and nothing else runs. The store is stdlib-only (no jax/numpy)
so tools and tests can import it without a backend.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

log = logging.getLogger(__name__)

# Module arming (core/spans.py discipline). `ARMED` gates the hot
# observer hook; `STORE` is the armed singleton the CLI wires into the
# debug endpoints and the black box.
ARMED = False
STORE: "MetricsTSDB | None" = None

# Default ring capacities: ~17 min of raw cycle samples at 2 s ticks,
# 10 min of 1 s buckets, 12 h of 1 m buckets. All per-series.
DEFAULT_RAW_CAP = 512
DEFAULT_SEC_CAP = 600
DEFAULT_MIN_CAP = 720

# Hard ceiling on distinct (family, labelset) series: a label-cardinality
# explosion degrades to dropped series + a counted complaint, never to
# unbounded memory.
MAX_SERIES = 4096

# Registry sample suffixes that would multiply series count without
# adding history value (bucketed histograms are reconstructible enough
# from _sum/_count for rule evaluation).
_SKIP_SUFFIXES = ("_bucket", "_created", "_gsum", "_gcount")


def _labels_key(labels: Any) -> tuple:
    """Normalizes a labels mapping to a hashable sorted tuple."""
    if not labels:
        return ()
    if isinstance(labels, tuple):
        return labels
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Series:
    """One (family, labelset) history: raw ring + 1 s / 1 m buckets.

    Writer side is externally serialized (MetricsTSDB._write_lock);
    readers are lock-free against the `commits` seqlock. Every slot is
    an immutable tuple so a torn read can only misorder the window,
    never expose a half-written point — the seqlock retry handles the
    ordering."""

    __slots__ = (
        "family", "labels", "commits", "total",
        "raw", "raw_n", "raw_cap",
        "sec", "sec_n", "sec_cap", "open_sec",
        "minute", "min_n", "min_cap", "open_min",
    )

    def __init__(self, family: str, labels: tuple,
                 raw_cap: int, sec_cap: int, min_cap: int):
        self.family = family
        self.labels = labels
        self.commits = 0
        self.total = 0
        self.raw: list = [None] * raw_cap
        self.raw_n = 0
        self.raw_cap = raw_cap
        self.sec: list = [None] * sec_cap
        self.sec_n = 0
        self.sec_cap = sec_cap
        self.open_sec: tuple | None = None
        self.minute: list = [None] * min_cap
        self.min_n = 0
        self.min_cap = min_cap
        self.open_min: tuple | None = None

    # -- writer side (serialized by MetricsTSDB._write_lock) ----------

    def append(self, t: float, v: float) -> None:
        self.raw[self.raw_n % self.raw_cap] = (t, v)
        self.raw_n += 1
        self.total += 1
        self.open_sec, flushed = self._fold(self.open_sec, float(int(t)), t, v)
        if flushed is not None:
            self.sec[self.sec_n % self.sec_cap] = flushed
            self.sec_n += 1
        self.open_min, flushed = self._fold(
            self.open_min, float(int(t // 60) * 60), t, v)
        if flushed is not None:
            self.minute[self.min_n % self.min_cap] = flushed
            self.min_n += 1
        # publish: single int store; CPython readers see it atomically
        self.commits += 1

    @staticmethod
    def _fold(bucket: tuple | None, bt: float, t: float, v: float):
        """Folds (t, v) into an aggregate bucket keyed by start time
        `bt`; returns (new_open_bucket, flushed_bucket_or_None)."""
        if bucket is None or bucket[0] != bt:
            return (bt, v, v, v, 1, v), bucket
        _, mn, mx, sm, cnt, _ = bucket
        return (bt, min(mn, v), max(mx, v), sm + v, cnt + 1, v), None

    # -- reader side (lock-free) --------------------------------------

    def _copy_ring(self, ring: list, n: int, cap: int, last: int) -> list:
        avail = min(n, cap)
        take = avail if last <= 0 else min(last, avail)
        start = n - take
        return [ring[i % cap] for i in range(start, n)]

    def snapshot(self, raw_last: int = 0, sec_last: int = 0,
                 min_last: int = 0) -> dict:
        """Seqlock-consistent copy of all three tiers (+ open buckets).
        `*_last` bound how much of each ring is copied (0 = all)."""
        out = None
        for _ in range(16):
            c0 = self.commits
            out = {
                "family": self.family,
                "labels": dict(self.labels),
                "total": self.total,
                "raw": self._copy_ring(
                    self.raw, self.raw_n, self.raw_cap, raw_last),
                "sec": self._copy_ring(
                    self.sec, self.sec_n, self.sec_cap, sec_last),
                "minute": self._copy_ring(
                    self.minute, self.min_n, self.min_cap, min_last),
                "open_sec": self.open_sec,
                "open_minute": self.open_min,
            }
            if self.commits == c0:
                return out
        # 16 consecutive torn windows means the writer is outrunning
        # us; the last copy is still made of immutable tuples (worst
        # case: one ring slightly newer than another). Bounded
        # staleness beats blocking the reader forever.
        return out


class MetricsTSDB:
    """Bounded in-process TSDB over scheduler metric families.

    See module docstring for the storage/concurrency model. The armed
    instance also drives the alert `RuleEngine` (metrics/rules.py) when
    one is attached via `self.engine`: evaluation is throttled to
    `eval_interval_s` and runs from whichever sampler fires next, so
    rules keep evaluating off the wall ticker even when the scheduling
    loop is wedged — exactly the case alerts exist for."""

    def __init__(self, raw_cap: int = DEFAULT_RAW_CAP,
                 sec_cap: int = DEFAULT_SEC_CAP,
                 min_cap: int = DEFAULT_MIN_CAP,
                 max_series: int = MAX_SERIES,
                 eval_interval_s: float = 1.0):
        self.raw_cap = max(16, int(raw_cap))
        self.sec_cap = max(16, int(sec_cap))
        self.min_cap = max(16, int(min_cap))
        self.max_series = max_series
        self.eval_interval_s = eval_interval_s
        self._series: dict[tuple[str, tuple], _Series] = {}
        self._write_lock = threading.Lock()
        self.dropped_series = 0
        self.engine = None  # metrics/rules.RuleEngine, attached by CLI
        self._last_eval = 0.0
        self._eval_lock = threading.Lock()
        self._ticker: threading.Thread | None = None
        self._ticker_stop = threading.Event()
        self.ticks = 0

    # ---- writer side -------------------------------------------------

    def append(self, family: str, labels: Any, value: float,
               t: float | None = None) -> None:
        """O(1) append of one sample; creates the series on first use."""
        key = (family, _labels_key(labels))
        t = time.time() if t is None else t
        with self._write_lock:
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return
                s = _Series(family, key[1],
                            self.raw_cap, self.sec_cap, self.min_cap)
                self._series[key] = s
            s.append(t, float(value))

    def observe_record(self, rec) -> None:
        """FlightRecorder observer hook: samples one committed cycle.

        First line is the whole unarmed cost (one global load + branch,
        core/spans.py ARMED discipline)."""
        if not ARMED:
            return
        try:
            t = rec.wall_start
            for phase, ms in rec.phases.items():
                self.append("cycle_phase_ms",
                            (("phase", phase),), ms, t=t)
            for k, v in rec.counts.items():
                self.append("cycle_count",
                            (("key", str(k)),), v, t=t)
        except Exception:
            # schedlint: disable=RB001 -- sampler must never take down
            # the scheduling loop; FlightRecorder detaches a raising
            # observer, so swallow + log here keeps us attached.
            log.exception("tsdb: cycle sample failed")
        self.maybe_evaluate()

    def sample_registry(self, registry) -> None:
        """Walks a prometheus CollectorRegistry collect() — i.e. one
        scrape, so `set_function` gauges evaluate — and appends every
        sample (histogram bucket fan-out excluded)."""
        t = time.time()
        try:
            families = list(registry.collect())
        except Exception:
            # schedlint: disable=RB001 -- a raising set_function gauge
            # (e.g. during shutdown teardown) must not kill the ticker.
            log.exception("tsdb: registry collect failed")
            return
        for fam in families:
            for sample in fam.samples:
                name = sample.name
                if name.endswith(_SKIP_SUFFIXES):
                    continue
                self.append(name, sample.labels, sample.value, t=t)

    # ---- rule-engine drive -------------------------------------------

    def maybe_evaluate(self, now: float | None = None) -> None:
        """Runs attached alert rules at most once per eval interval."""
        eng = self.engine
        if eng is None:
            return
        now = time.time() if now is None else now
        with self._eval_lock:
            if now - self._last_eval < self.eval_interval_s:
                return
            self._last_eval = now
            try:
                eng.evaluate(now)
            except Exception:
                # schedlint: disable=RB001 -- rule evaluation is
                # advisory; it must never block sampling or the loop.
                log.exception("tsdb: rule evaluation failed")

    # ---- wall ticker -------------------------------------------------

    def start_ticker(self, registry, interval_s: float = 2.0,
                     extra: Callable[[], None] | None = None) -> None:
        """Starts the low-frequency sampler thread for scrape-time
        gauges. Idempotent; `stop_ticker`/`disarm` joins it."""
        if self._ticker is not None or interval_s <= 0:
            return
        self._ticker_stop.clear()

        def _run():
            while not self._ticker_stop.wait(interval_s):
                self.sample_registry(registry)
                if extra is not None:
                    try:
                        extra()
                    except Exception:
                        # schedlint: disable=RB001 -- auxiliary sampler
                        # must not kill the ticker thread.
                        log.exception("tsdb: extra sampler failed")
                self.ticks += 1
                self.maybe_evaluate()

        self._ticker = threading.Thread(
            target=_run, name="metrics-tsdb-ticker", daemon=True)
        self._ticker.start()

    def stop_ticker(self) -> None:
        th = self._ticker
        if th is None:
            return
        self._ticker_stop.set()
        th.join(timeout=5.0)
        self._ticker = None

    # ---- reader side -------------------------------------------------

    def _match(self, family: str | None,
               labels: dict | None) -> list[_Series]:
        want = _labels_key(labels) if labels else ()
        out = []
        for (fam, lk), s in list(self._series.items()):
            if family and fam != family:
                continue
            if want and not set(want).issubset(set(lk)):
                continue
            out.append(s)
        return out

    def query(self, family: str, labels: dict | None = None,
              window_s: float = 300.0, step_s: float = 0.0,
              now: float | None = None) -> dict:
        """History query for `/debug/metrics/history` and the rules
        engine. Tier selection: step >= 60 -> 1 m buckets, step >= 1 ->
        1 s buckets, else raw points. Points within [now - window, now];
        aggregate tiers return [t, min, max, sum, count, last] rows,
        raw returns [t, value]."""
        now = time.time() if now is None else now
        lo = now - max(0.0, float(window_s))
        tier = "1m" if step_s >= 60 else ("1s" if step_s >= 1 else "raw")
        series_out = []
        for s in self._match(family, labels):
            snap = s.snapshot()
            if tier == "raw":
                pts = [[t, v] for (t, v) in snap["raw"] if t >= lo]
            else:
                ring = snap["sec"] if tier == "1s" else snap["minute"]
                open_b = (snap["open_sec"] if tier == "1s"
                          else snap["open_minute"])
                buckets = list(ring)
                if open_b is not None:
                    buckets.append(open_b)
                pts = [list(b) for b in buckets if b[0] >= lo]
            series_out.append({
                "labels": snap["labels"],
                "total_samples": snap["total"],
                "points": pts,
            })
        return {"family": family, "tier": tier, "now": now,
                "window_s": window_s, "series": series_out}

    def families(self) -> list[dict]:
        """Inventory of stored series for endpoint discovery."""
        rows: dict[str, dict] = {}
        for (fam, lk), s in sorted(self._series.items()):
            row = rows.setdefault(fam, {"family": fam, "series": 0,
                                        "samples": 0})
            row["series"] += 1
            row["samples"] += s.total
        return list(rows.values())

    def status(self) -> dict:
        return {
            "armed": ARMED,
            "series": len(self._series),
            "dropped_series": self.dropped_series,
            "ticks": self.ticks,
            "caps": {"raw": self.raw_cap, "sec": self.sec_cap,
                     "minute": self.min_cap},
        }

    def snapshot_all(self, raw_last: int = 128, sec_last: int = 120,
                     min_last: int = 120) -> dict:
        """Bounded full dump for the black box: every series' recent
        window across all tiers."""
        return {
            "status": self.status(),
            "series": [s.snapshot(raw_last=raw_last, sec_last=sec_last,
                                  min_last=min_last)
                       for s in self._match(None, None)],
        }


def arm(store: MetricsTSDB | None = None, **kwargs) -> MetricsTSDB:
    """Arms the module (and creates the store unless one is passed).
    Returns the armed store; callers attach `observe_record` to their
    FlightRecorder and optionally `start_ticker`."""
    global ARMED, STORE
    if store is None:
        store = STORE if STORE is not None else MetricsTSDB(**kwargs)
    STORE = store
    ARMED = True
    return store


def disarm() -> None:
    """Disarms sampling and stops the ticker thread. The store object
    stays valid for post-mortem reads (black box dumps at shutdown)."""
    global ARMED, STORE
    ARMED = False
    store, STORE = STORE, None
    if store is not None:
        store.stop_ticker()
