"""Config API: the KubeSchedulerConfiguration analogue.

Mirrors the reference's versioned config (`apis/config/` — [UNVERIFIED],
mount empty; SURVEY.md §2 C12): profiles keyed by schedulerName, per-
extension-point plugin enable/disable lists, per-plugin args, and the
`percentageOfNodesToScore` knob, loadable from the same YAML field names.
No multi-version conversion machinery (SURVEY.md §5.6)."""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any


@dataclass
class PluginEntry:
    name: str
    weight: int = 1


@dataclass
class PluginSet:
    enabled: list[PluginEntry] = field(default_factory=list)
    disabled: list[str] = field(default_factory=list)  # ["*"] = all defaults

    def resolve(self, defaults: list[PluginEntry]) -> list[PluginEntry]:
        """Upstream merge semantics: defaults minus disabled, plus enabled
        (enabled entries replace same-named defaults to carry new weights)."""
        if "*" in self.disabled:
            base: list[PluginEntry] = []
        else:
            base = [d for d in defaults if d.name not in self.disabled]
        out = {e.name: e for e in base}
        for e in self.enabled:
            out[e.name] = e
        return list(out.values())


@dataclass
class Plugins:
    queue_sort: PluginSet = field(default_factory=PluginSet)
    pre_filter: PluginSet = field(default_factory=PluginSet)
    filter: PluginSet = field(default_factory=PluginSet)
    post_filter: PluginSet = field(default_factory=PluginSet)
    pre_score: PluginSet = field(default_factory=PluginSet)
    score: PluginSet = field(default_factory=PluginSet)
    reserve: PluginSet = field(default_factory=PluginSet)
    permit: PluginSet = field(default_factory=PluginSet)
    bind: PluginSet = field(default_factory=PluginSet)


@dataclass
class Profile:
    scheduler_name: str = "default-scheduler"
    plugins: Plugins = field(default_factory=Plugins)
    plugin_config: dict[str, dict[str, Any]] = field(default_factory=dict)


@dataclass
class Extender:
    """HTTP scheduler-extender config (upstream `Extender` in
    KubeSchedulerConfiguration): filter/prioritize/bind delegation to an
    external webhook."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    weight: int = 1
    http_timeout_seconds: float = 5.0
    # errors from an ignorable extender don't fail the pod's attempt
    ignorable: bool = False
    # operator assertion that this extender's Filter/Prioritize verdicts
    # depend only on (pod, node set) — i.e. are DETERMINISTIC per pod.
    # When every configured extender sets this, the scheduler keeps the
    # device-carry latency path: verdict rows live on device and only
    # CHANGED pods re-consult the webhook each cycle (PERF.md "Extenders
    # and the carry path"). Off by default: upstream extenders may be
    # stateful, and those must be re-consulted for every pod each cycle
    # (the full-path behavior).
    carry_verdicts: bool = False


@dataclass
class SchedulerConfiguration:
    profiles: list[Profile] = field(default_factory=lambda: [Profile()])
    percentage_of_nodes_to_score: int = 0  # 0 = adaptive/all (upstream default)
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    # gang scheduling (Coscheduling PodGroup CRD analogue, SURVEY.md C14)
    gang_scheduling: bool = True
    # in-cycle commitment engine (TPU-native extension, see ops/rounds.py):
    # "rounds" = batched round commit (production default at scale),
    # "scan" = strict sequential per-pod scan (exact ScheduleOne order)
    commit_mode: str = "rounds"
    extenders: list[Extender] = field(default_factory=list)
    # sticky-regime pre-sizing (TPU-native extension): a fold-heavy
    # deployment (bindings folded into the existing set every cycle)
    # should pre-size the existing-pod pad to its steady-state count and
    # the per-node victim-table depth to its hot-node depth, so the
    # packed regime never flips mid-serving — a flip costs a full
    # recompile and has tripped a rig-side executable wedge (PERF.md
    # "fold-mode rig wedge"). 0 = size from the first snapshot.
    pad_existing: int = 0
    pad_pods_per_node: int = 0
    # pre-size the sticky per-pod term pads the same way (ADVICE r5): MA
    # = (anti-)affinity/preferred terms per pod, MC = topology-spread
    # constraints per pod. Both bucket by 2, so a mid-serving arrival of
    # a 3-4-term pod otherwise flips the regime. 0 = size from the first
    # snapshot.
    pad_ma: int = 0
    pad_mc: int = 0
    # serving-pipeline escape hatch: block every cycle dispatch to
    # completion before continuing (strict sequential execution —
    # identical results, no overlap). For tests and latency measurement;
    # production serving leaves this False and overlaps preemption/
    # diagnosis/transfer with host bind work (core/pipeline.py).
    forced_sync: bool = False
    # cycle flight recorder (core/flight_recorder.py): ring capacity for
    # per-cycle phase records — feeds /debug/flightrecorder, the
    # /debug/trace Perfetto export, the per-pod timelines, and the
    # derived pipeline gauges. 0 disables recording entirely.
    flight_recorder_size: int = 512
    # /healthz staleness deadline: report 503 when no scheduling cycle
    # completed within this many seconds (0 = never go stale). Uses the
    # flight recorder's last-cycle age, so a wedged scheduler stops
    # reporting healthy (cmd/main.py).
    health_max_cycle_age_seconds: float = 0.0
    # latency SLO (core/observe.py): objective "p99 of cycle wall time
    # <= sloP99Ms over sloWindowCycles cycles" (i.e. at most 1% of the
    # window's cycles may exceed the bound). Drives the
    # scheduler_slo_burn_rate{window} / scheduler_slo_budget_remaining
    # gauges and the /healthz degraded flag on a fast-window burn.
    # 0 disables the objective (attribution + anomalies still run).
    slo_p99_ms: float = 0.0
    slo_window_cycles: int = 1024
    # multi-cycle on-device serving (core/cycle.build_packed_multicycle_fn):
    # coalesce up to K per-cycle arrival groups into ONE device dispatch
    # running K scheduling cycles in a device-resident loop, amortizing
    # the remote-dispatch round trip K-fold for small-delta cycles.
    # 1 disables batching (every cycle dispatches alone). Workloads
    # outside the exactness envelope (inter-pod affinity, topology
    # spread, volumes, pending host ports, extenders) automatically fall
    # back to sequential single-cycle dispatches.
    multi_cycle_k: int = 1
    # latency bound on the coalescing buffer: a delta group is never
    # held back longer than this many milliseconds waiting for the
    # batch to fill (an idle pop also flushes immediately)
    multi_cycle_max_wait_ms: float = 5.0
    # compile-regime management (core/compile_cache.py):
    # padHysteresisPct — down-step margin for the P/N pad buckets: a
    # shrinking pending/node count only steps the pad regime DOWN when
    # it leaves at least this many percent of headroom inside the
    # smaller bucket, so a workload oscillating around a bucket
    # boundary holds the larger (already-compiled) regime instead of
    # flip-flopping. 0 disables (immediate down-step).
    pad_hysteresis_pct: float = 0.0
    # compileCacheDir — directory for the persistent compiled-program
    # cache (AOT executables keyed by pad regime + profile + program
    # kind + jaxlib/backend fingerprint). "" derives
    # <stateDir>/compile_cache when stateDir is set, else disables;
    # "off"/"none" disables even with a state dir (slow shared
    # storage, poisoned-cache triage). A warm restart then compiles
    # zero programs for previously-seen regimes (entry load ~<1 s vs
    # the 8.8-16.8 s cold compile).
    compile_cache_dir: str = ""
    # shardDevices — shard the serving path's device-resident carry
    # (the [P, N] static base and [S, P] matched-pending tables) over a
    # 1-D ('pods',) jax.sharding.Mesh of this many local devices; the
    # claim path's shard-invariant tie-breaking (ops/argsel.py) keeps
    # placements bit-identical to the single-device run at any count.
    # 0/1 disables (everything stays on one device). Must divide the
    # pod pad bucket (64) and not exceed jax.devices().
    shard_devices: int = 0
    # speculativeCompile — background pre-compilation of the ADJACENT
    # pad regime on a warm thread (never the bind path) when the
    # anomaly sentinel's demand EWMA drifts toward a bucket boundary;
    # a flip speculation won costs ~0 compile on the serve path.
    speculative_compile: bool = True
    # speculativeDispatch — depth-2 speculative dispatch pipelining
    # (core/pipeline.py + core/scheduler.py): while multi-cycle batch k
    # is on device, speculatively dispatch batch k+1 against the
    # predicted post-k carry (device-resident continuation chaining).
    # When batch k's host fold lands, the speculation is adopted on a
    # predicate-digest match (zero added latency) or abandoned and
    # re-dispatched against the true carry — bit-identical results
    # either way, only latency is speculative. Effective on the
    # multi-cycle path (multiCycleK > 1); forced off under forcedSync
    # and at/below the degradation ladder's `sequential` rung.
    speculative_dispatch: bool = True
    # incrementalEncode — admission-time incremental encode
    # (models/encoding.py ingest_pod + core/scheduler.py multi-cycle
    # flush): each pod buffered for a multi-cycle batch is parsed into
    # staged row data at buffer time, in the ack path's shadow, so the
    # flush-time encode is an O(dirty) finalize over pre-parsed rows
    # instead of an O(P) re-walk. Falls back to a full rebuild whenever
    # an interning table grows during ingest or the pad regime flips —
    # the packed arena is bit-identical either way. Effective on the
    # multi-cycle path (multiCycleK > 1); a no-op at K=1.
    incremental_encode: bool = False
    # dispatch watchdog (core/pipeline.py): bound, in milliseconds, on
    # the ONE blocking device->host decision fetch. On expiry the fetch
    # is abandoned (DispatchDeadlineExceeded), the cycle's pods requeue
    # with backoff, and the degradation ladder (core/degrade.py) steps
    # down one rung — a hung tunnel can no longer wedge the serve loop
    # forever. 0 disables the bound (the pre-watchdog behavior).
    dispatch_deadline_ms: float = 0.0
    # degradation ladder promotion: after this many consecutive clean
    # scheduling cycles (dispatches that completed without a failure)
    # the ladder steps one rung back up toward `normal`.
    degrade_promote_cycles: int = 8
    # fault injection (core/faults.py): a FaultPlan spec like
    # "fetch_hang@cycle=40:ms=5000" — scripted, seeded faults fired at
    # named points on the real code paths (soaks/benches/tests only;
    # env SCHED_FAULTS overrides when this is empty). "" disarms.
    fault_spec: str = ""
    # submission front door (service/admission.py): bound on the
    # admission queue — pending pods (all queue tiers) plus pods
    # coalescing in the multi-cycle buffers. A Submit that would push
    # the depth past this bound is SHED whole (RESOURCE_EXHAUSTED +
    # retry-after), never buffered: overload degrades to shedding, not
    # to unbounded memory. Shedding also engages while the SLO
    # fast-burn gauge fires or the degradation ladder sits below rung
    # 0. 0 disables the front door's depth bound (tests only).
    admission_queue_depth: int = 65536
    # retry-after hint (milliseconds) attached to shed submissions —
    # gRPC trailing metadata "retry-after-ms" and the HTTP
    # Retry-After header on the debug server's POST /submit path.
    admission_retry_after_ms: float = 250.0
    # pod-lifecycle tracing (core/spans): head-sampling probability
    # for submissions that arrive WITHOUT an explicit traceparent —
    # deterministic per pod uid, so a shed retry keeps its sampling
    # fate. An explicit traceparent always samples. 0 disables
    # arming entirely (stamp sites pay one flag load); 1 traces every
    # pod (bench overhead stages and acceptance runs).
    trace_sample_rate: float = 1.0 / 64.0
    # durable scheduler state (state/ package): directory for the
    # write-ahead journal + snapshots. "" disables durability — a
    # takeover then rebuilds only what informer events re-deliver,
    # losing backoff deadlines, attempt counts, and assumed pods.
    state_dir: str = ""
    # snapshot cadence: how often the journal is compacted into a full
    # snapshot (seconds; 0 = journal only, never compact)
    snapshot_interval_seconds: float = 60.0
    # watchtower (metrics/tsdb.py + metrics/rules.py): per-series raw
    # ring capacity of the in-process metrics history store. The CLI
    # arms the TSDB + the built-in alert rule pack when > 0; 0 disables
    # the whole watchtower (history, rules, dashboard) — the unarmed
    # cost at the cycle hook is one module-flag check.
    metrics_history_samples: int = 512
    # wall-ticker cadence (seconds) for scrape-time gauges: the TSDB
    # samples the full Prometheus registry — set_function gauges
    # evaluate exactly as on a /metrics GET — every this-many seconds.
    # 0 disables the ticker (cycle-driven samples only).
    metrics_ticker_seconds: float = 2.0
    # extra alert rules (YAML/JSON list of rule objects, the
    # metrics/rules.py shape) appended to the built-in pack. "" = the
    # built-in pack only.
    alert_rules_file: str = ""
    # crash black box (core/blackbox.py): how many post-mortem bundles
    # to keep under <stateDir>/blackbox/ (oldest deleted first; also
    # capped at 64 MB total). 0 disables black-box capture. Needs
    # stateDir — the bundle directory lives next to the journal.
    blackbox_retention: int = 8
    # /debug/dashboard HTML sparkline page (needs the watchtower
    # armed); False turns just the page off, the history/alerts JSON
    # endpoints stay.
    debug_dashboard: bool = True

    def profile(self, scheduler_name: str = "default-scheduler") -> Profile:
        for p in self.profiles:
            if p.scheduler_name == scheduler_name:
                return p
        return self.profiles[0]


# Upstream default plugin sets (getDefaultPlugins — [UNVERIFIED] weights
# follow the widely-documented defaults: PodTopologySpread 2,
# TaintToleration 3, others 1).
_DEFAULT_FILTERS = [
    "NodeUnschedulable",
    "NodeName",
    "TaintToleration",
    "NodeAffinity",
    "NodePorts",
    "NodeResourcesFit",
    "VolumeBinding",
    "InterPodAffinity",
    "PodTopologySpread",
]
_DEFAULT_SCORES = [
    ("NodeResourcesBalancedAllocation", 1),
    ("ImageLocality", 1),
    ("InterPodAffinity", 1),
    ("NodeResourcesFit", 1),
    ("NodeAffinity", 1),
    ("PodTopologySpread", 2),
    ("TaintToleration", 3),
]
_DEFAULT_POST_FILTERS = ["DefaultPreemption"]


def default_plugins() -> dict[str, list[PluginEntry]]:
    return {
        "filter": [PluginEntry(n) for n in _DEFAULT_FILTERS],
        "score": [PluginEntry(n, w) for n, w in _DEFAULT_SCORES],
        "post_filter": [PluginEntry(n) for n in _DEFAULT_POST_FILTERS],
    }


def _duration_seconds(v) -> float:
    """Upstream serializes durations as strings ('5s', '500ms', '1m30s');
    accept those and plain numbers."""
    if isinstance(v, (int, float)):
        return float(v)
    import re

    total = 0.0
    for num, unit in re.findall(r"([0-9.]+)(h|m(?!s)|s|ms|us|ns)", str(v)):
        total += float(num) * {
            "h": 3600.0, "m": 60.0, "s": 1.0,
            "ms": 1e-3, "us": 1e-6, "ns": 1e-9,
        }[unit]
    return total or 5.0


def _plugin_set_from_dict(d: dict) -> PluginSet:
    return PluginSet(
        enabled=[
            PluginEntry(e["name"], e.get("weight", 1)) for e in d.get("enabled", [])
        ],
        disabled=[e["name"] if isinstance(e, dict) else e
                  for e in d.get("disabled", [])],
    )


def load_config(source: "str | dict") -> SchedulerConfiguration:
    """Load from a YAML string/path or a dict with upstream field names."""
    if isinstance(source, str):
        import yaml

        if "\n" not in source and source.endswith((".yaml", ".yml", ".json")):
            with open(source) as f:
                data = yaml.safe_load(f)
        else:
            data = yaml.safe_load(source)
    else:
        data = source
    data = data or {}

    profiles = []
    for pd in data.get("profiles", [{}]):
        plugins = Plugins()
        for point, attr in [
            ("queueSort", "queue_sort"),
            ("preFilter", "pre_filter"),
            ("filter", "filter"),
            ("postFilter", "post_filter"),
            ("preScore", "pre_score"),
            ("score", "score"),
            ("reserve", "reserve"),
            ("permit", "permit"),
            ("bind", "bind"),
        ]:
            if point in pd.get("plugins", {}):
                setattr(plugins, attr, _plugin_set_from_dict(pd["plugins"][point]))
        plugin_config = {
            e["name"]: e.get("args", {}) for e in pd.get("pluginConfig", [])
        }
        profiles.append(
            Profile(
                scheduler_name=pd.get("schedulerName", "default-scheduler"),
                plugins=plugins,
                plugin_config=plugin_config,
            )
        )
    return SchedulerConfiguration(
        profiles=profiles or [Profile()],
        percentage_of_nodes_to_score=data.get("percentageOfNodesToScore", 0),
        pod_initial_backoff_seconds=data.get("podInitialBackoffSeconds", 1.0),
        pod_max_backoff_seconds=data.get("podMaxBackoffSeconds", 10.0),
        gang_scheduling=data.get("gangScheduling", True),
        commit_mode=data.get("commitMode", "rounds"),
        pad_existing=int(data.get("padExisting", 0)),
        pad_pods_per_node=int(data.get("padPodsPerNode", 0)),
        pad_ma=int(data.get("padMa", 0)),
        pad_mc=int(data.get("padMc", 0)),
        forced_sync=bool(data.get("forcedSync", False)),
        flight_recorder_size=int(data.get("flightRecorderSize", 512)),
        health_max_cycle_age_seconds=_duration_seconds(
            data.get("healthMaxCycleAge", 0.0)
        ),
        slo_p99_ms=float(data.get("sloP99Ms", 0.0)),
        slo_window_cycles=int(data.get("sloWindowCycles", 1024)),
        multi_cycle_k=int(data.get("multiCycleK", 1)),
        multi_cycle_max_wait_ms=float(data.get("multiCycleMaxWaitMs", 5.0)),
        pad_hysteresis_pct=float(data.get("padHysteresisPct", 0.0)),
        compile_cache_dir=str(data.get("compileCacheDir", "")),
        shard_devices=int(data.get("shardDevices", 0)),
        speculative_compile=bool(data.get("speculativeCompile", True)),
        speculative_dispatch=bool(data.get("speculativeDispatch", True)),
        incremental_encode=bool(data.get("incrementalEncode", False)),
        dispatch_deadline_ms=float(data.get("dispatchDeadlineMs", 0.0)),
        degrade_promote_cycles=int(data.get("degradePromoteCycles", 8)),
        fault_spec=str(data.get("faultSpec", "")),
        admission_queue_depth=int(data.get("admissionQueueDepth", 65536)),
        admission_retry_after_ms=float(
            data.get("admissionRetryAfterMs", 250.0)
        ),
        trace_sample_rate=float(
            data.get("traceSampleRate", 1.0 / 64.0)
        ),
        state_dir=str(data.get("stateDir", "")),
        snapshot_interval_seconds=_duration_seconds(
            data.get("snapshotInterval", 60.0)
        ),
        metrics_history_samples=int(
            data.get("metricsHistorySamples", 512)
        ),
        metrics_ticker_seconds=float(
            data.get("metricsTickerSeconds", 2.0)
        ),
        alert_rules_file=str(data.get("alertRulesFile", "")),
        blackbox_retention=int(data.get("blackboxRetention", 8)),
        debug_dashboard=bool(data.get("debugDashboard", True)),
        extenders=[
            Extender(
                url_prefix=e["urlPrefix"],
                filter_verb=e.get("filterVerb", ""),
                prioritize_verb=e.get("prioritizeVerb", ""),
                bind_verb=e.get("bindVerb", ""),
                weight=e.get("weight", 1),
                http_timeout_seconds=_duration_seconds(
                    e.get("httpTimeout", 5.0)
                ),
                ignorable=e.get("ignorable", False),
                carry_verdicts=e.get("carryVerdicts", False),
            )
            for e in data.get("extenders", [])
        ],
    )


def to_dict(cfg: SchedulerConfiguration) -> dict:
    return dataclasses.asdict(cfg)
