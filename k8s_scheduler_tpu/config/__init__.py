from .types import (  # noqa: F401
    PluginEntry,
    Plugins,
    PluginSet,
    Profile,
    SchedulerConfiguration,
    default_plugins,
    load_config,
)
