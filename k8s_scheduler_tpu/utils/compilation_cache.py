"""Persistent XLA compilation cache.

The config-#4 cycle takes 100-170s to compile; upstream kube-scheduler
restarts in seconds, so a TPU scheduler that recompiles its programs on
every process start would be an operational regression (leader failover,
rolling restarts). Enabling JAX's persistent compilation cache brings a
warm restart's compile cost to ~1s per program (measured on the axon
backend: 6.7s -> 0.75s for a synthetic probe; the real cycle similarly).

Called from the CLI entrypoint, the bench suite, and tests' conftest.
"""

from __future__ import annotations

import os


def enable_compilation_cache(path: str | None = None) -> str:
    """Idempotently point JAX at a persistent on-disk compilation cache.
    Honors JAX_COMPILATION_CACHE_DIR when set; returns the directory.

    The directory is scoped PER PRIMARY BACKEND: an accelerator-attached
    process compiles its host-side XLA:CPU programs with the plugin's
    CPU tuning flags (+prefer-no-scatter/-gather here), and a pure-CPU
    process loading those entries gets machine-feature mismatches and,
    worse, executables whose buffer layout disagrees with the fresh
    trace ("supplied 6 buffers but compiled program expected 7").
    Separate directories keep each backend's entries self-consistent."""
    import jax

    if os.environ.get("K8S_TPU_DISABLE_COMPILE_CACHE") == "1":
        return ""
    base = (
        path
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "k8s_scheduler_tpu_jax"
        )
    )
    d = os.path.join(base, jax.default_backend())
    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", d)
    # cache everything that takes real time; tiny programs stay in-memory
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    return d
