from .quantity import parse_quantity  # noqa: F401
