"""Kubernetes resource-quantity parsing.

The reference family parses quantities with `k8s.io/apimachinery`'s
`resource.Quantity` (suffixes m, k/M/G/T/P/E, Ki/Mi/Gi/Ti/Pi/Ei, scientific
notation). Scheduling only needs a scalar ordering + arithmetic, so we
normalize every quantity to a float:

- cpu-like quantities: parsed to *millicores* when `as_millis=True`
  (the scheduler's internal cpu unit, matching upstream MilliCPU).
- everything else: absolute value (bytes for memory).

Expected upstream location (fork mount was empty, [UNVERIFIED] per
SURVEY.md): vendored apimachinery `pkg/api/resource/quantity.go`.
"""

from __future__ import annotations

_BIN = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DEC = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}


def parse_quantity(q: "str | int | float", as_millis: bool = False) -> float:
    """Parse a k8s quantity string (or passthrough number) to a float.

    >>> parse_quantity("100m", as_millis=True)
    100.0
    >>> parse_quantity("2", as_millis=True)
    2000.0
    >>> parse_quantity("1Gi")
    1073741824.0
    """
    if isinstance(q, (int, float)):
        val = float(q)
        return val * 1000.0 if as_millis else val
    s = q.strip()
    if not s:
        raise ValueError("empty quantity")
    for suf, mult in _BIN.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mult * (1000.0 if as_millis else 1.0)
    # Single-char decimal suffix. Scientific notation ("1e3") ends in a
    # digit, so it never collides; a bare trailing e/E ("5E" = 5 exa) does
    # not parse as a float, which the try below distinguishes.
    if len(s) > 1 and s[-1] in _DEC:
        try:
            val = float(s[:-1]) * _DEC[s[-1]]
        except ValueError:
            val = float(s)
    else:
        val = float(s)
    return val * 1000.0 if as_millis else val


def format_millis(millis: float) -> str:
    """Inverse of parse_quantity(as_millis=True): exact round-trip, so
    sub-millicore requests ("500u" = 0.5m) survive the wire ("1500m",
    "500u", "2")."""
    if millis == int(millis):
        if int(millis) % 1000 == 0:
            return str(int(millis) // 1000)
        return f"{int(millis)}m"
    nanos = millis * 1e6  # millicores -> nanocores
    if nanos == int(nanos) and int(nanos) % 1000 == 0:
        return f"{int(nanos) // 1000}u"
    return f"{int(round(nanos))}n"
