"""Synthetic cluster generation — the fixture generator for unit, property,
and perf tests alike (SURVEY.md §4: nodes are just API objects, "multi-node"
needs no machines; this mirrors upstream scheduler_perf's YAML workload
templates as parameterized generators)."""

from __future__ import annotations

import numpy as np

from ..models.api import Node, Pod, PodGroup
from ..models.builders import MakeNode, MakePod

ZONES = [f"zone-{c}" for c in "abcdef"]
REGIONS = ["region-1", "region-2"]


def make_cluster(
    num_nodes: int,
    seed: int = 0,
    with_labels: bool = True,
    taint_fraction: float = 0.0,
    cpu_choices: tuple[int, ...] = (8, 16, 32, 64),
    memory_choices: tuple[int, ...] = (16, 32, 64, 128),
) -> list[Node]:
    """`cpu_choices`/`memory_choices` set the per-node capacity draw —
    scarcity knobs for preemption-heavy benchmark configs."""
    rng = np.random.default_rng(seed)
    nodes = []
    for i in range(num_nodes):
        b = MakeNode(f"node-{i}").capacity(
            {
                "cpu": f"{int(rng.choice(cpu_choices))}",
                "memory": f"{int(rng.choice(memory_choices))}Gi",
                "pods": 110,
            }
        )
        if with_labels:
            b.labels(
                {
                    "topology.kubernetes.io/zone": ZONES[i % len(ZONES)],
                    "topology.kubernetes.io/region": REGIONS[i % len(REGIONS)],
                    "node-type": ["general", "compute", "memory"][i % 3],
                }
            )
        if taint_fraction and rng.random() < taint_fraction:
            b.taint("dedicated", "special")
        nodes.append(b.obj())
    return nodes


def make_pods(
    num_pods: int,
    seed: int = 1,
    name_prefix: str = "pod",
    affinity_fraction: float = 0.0,
    anti_affinity_fraction: float = 0.0,
    selector_fraction: float = 0.0,
    toleration_fraction: float = 0.0,
    spread_fraction: float = 0.0,
    priorities: tuple[int, ...] = (0,),
    num_apps: int = 20,
) -> list[Pod]:
    """`num_apps` controls how many distinct `app` labels (and therefore
    distinct affinity selectors) the workload carries — the S axis of the
    affinity state; real clusters run one selector per deployment, so
    realistic scale tests want num_apps in the hundreds."""
    rng = np.random.default_rng(seed)
    pods = []
    for i in range(num_pods):
        app = f"app-{int(rng.integers(0, num_apps))}"
        b = (
            MakePod(f"{name_prefix}-{i}")
            .req(
                {
                    "cpu": f"{int(rng.integers(1, 16)) * 250}m",
                    "memory": f"{int(rng.integers(1, 16)) * 256}Mi",
                }
            )
            .labels({"app": app})
            .priority(int(rng.choice(priorities)))
            .created(float(i))
        )
        if selector_fraction and rng.random() < selector_fraction:
            b.node_selector({"node-type": ["general", "compute", "memory"][i % 3]})
        if toleration_fraction and rng.random() < toleration_fraction:
            b.toleration("dedicated", "special", "NoSchedule")
        if affinity_fraction and rng.random() < affinity_fraction:
            b.pod_affinity("topology.kubernetes.io/zone", {"app": app})
        if anti_affinity_fraction and rng.random() < anti_affinity_fraction:
            b.pod_affinity("kubernetes.io/hostname", {"app": app}, anti=True)
        if spread_fraction and rng.random() < spread_fraction:
            b.spread(2, "topology.kubernetes.io/zone", {"app": app})
        pods.append(b.obj())
    return pods


def make_gang_pods(
    num_groups: int, replicas: int = 8, seed: int = 2
) -> tuple[list[Pod], list[PodGroup]]:
    rng = np.random.default_rng(seed)
    pods, groups = [], []
    for g in range(num_groups):
        name = f"job-{g}"
        groups.append(PodGroup(name, replicas))
        for r in range(replicas):
            pods.append(
                MakePod(f"{name}-{r}")
                .req({"cpu": f"{int(rng.integers(2, 8)) * 500}m",
                      "memory": "1Gi"})
                .group(name)
                .created(float(g * replicas + r))
                .obj()
            )
    return pods, groups
