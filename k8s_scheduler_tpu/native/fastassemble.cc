// Native snapshot-row assembly (SURVEY.md §2 C-notes: "C++ encoder if
// Python encoding becomes the bottleneck" — it did: the per-pod Python
// array writes dominate steady-state re-encode).
//
// One exported function per access pattern, CPython C API + the buffer
// protocol only (no pybind11 in this image):
//
//   scatter_rows(dst, rows, width)
//       dst: 2-D C-contiguous numpy array [R, W_dst]
//       rows: list of 1-D arrays (same dtype), row i copied into
//             dst[i, :len(rows[i])]; rows beyond width are truncated.
//   scatter_rows_at(dst, index, rows)
//       like scatter_rows but row i goes to dst[index[i], :].
//
// The Python encoder falls back to per-row numpy writes when this
// module isn't built (k8s_scheduler_tpu/native/__init__.py), so the
// extension is an accelerator, not a dependency. Build: `make -C
// k8s_scheduler_tpu/native` (or setup.py build_ext --inplace).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>

namespace {

struct View {
  Py_buffer buf{};
  bool ok = false;
  ~View() {
    if (ok) PyBuffer_Release(&buf);
  }
  bool acquire(PyObject* obj, int flags) {
    if (PyObject_GetBuffer(obj, &buf, flags) != 0) return false;
    ok = true;
    return true;
  }
};

// dst[i or index[i], :len(row_i)] = row_i for every row in `rows`.
PyObject* scatter_impl(PyObject* dst_obj, PyObject* index_obj,
                       PyObject* rows_obj) {
  View dst;
  if (!dst.acquire(dst_obj, PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE)) {
    return nullptr;
  }
  if (dst.buf.ndim != 2) {
    PyErr_SetString(PyExc_ValueError, "dst must be 2-D");
    return nullptr;
  }
  const Py_ssize_t n_rows = dst.buf.shape[0];
  const Py_ssize_t width_bytes = dst.buf.shape[1] * dst.buf.itemsize;
  char* base = static_cast<char*>(dst.buf.buf);

  View index;
  const long* idx = nullptr;
  Py_ssize_t n_idx = 0;
  if (index_obj != nullptr && index_obj != Py_None) {
    if (!index.acquire(index_obj, PyBUF_C_CONTIGUOUS)) return nullptr;
    if (index.buf.ndim != 1 || index.buf.itemsize != sizeof(long)) {
      PyErr_SetString(PyExc_ValueError, "index must be 1-D int64");
      return nullptr;
    }
    idx = static_cast<const long*>(index.buf.buf);
    n_idx = index.buf.shape[0];
  }

  PyObject* seq = PySequence_Fast(rows_obj, "rows must be a sequence");
  if (seq == nullptr) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (idx != nullptr && n > n_idx) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "index shorter than rows");
    return nullptr;
  }

  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* row = PySequence_Fast_GET_ITEM(seq, i);  // borrowed
    if (row == Py_None) continue;
    View rv;
    if (!rv.acquire(row, PyBUF_C_CONTIGUOUS)) {
      Py_DECREF(seq);
      return nullptr;
    }
    if (rv.buf.itemsize != dst.buf.itemsize) {
      Py_DECREF(seq);
      PyErr_Format(PyExc_ValueError,
                   "row %zd itemsize %zd != dst itemsize %zd", i,
                   rv.buf.itemsize, dst.buf.itemsize);
      return nullptr;
    }
    const Py_ssize_t target = idx ? idx[i] : i;
    if (target < 0 || target >= n_rows) {
      Py_DECREF(seq);
      PyErr_Format(PyExc_IndexError, "row %zd target %zd out of range", i,
                   target);
      return nullptr;
    }
    Py_ssize_t bytes = rv.buf.len;
    if (bytes > width_bytes) bytes = width_bytes;  // truncate to dst width
    std::memcpy(base + target * width_bytes, rv.buf.buf,
                static_cast<size_t>(bytes));
  }
  Py_DECREF(seq);
  Py_RETURN_NONE;
}

PyObject* scatter_rows(PyObject*, PyObject* args) {
  PyObject* dst;
  PyObject* rows;
  if (!PyArg_ParseTuple(args, "OO", &dst, &rows)) return nullptr;
  return scatter_impl(dst, nullptr, rows);
}

PyObject* scatter_rows_at(PyObject*, PyObject* args) {
  PyObject* dst;
  PyObject* index;
  PyObject* rows;
  if (!PyArg_ParseTuple(args, "OOO", &dst, &index, &rows)) return nullptr;
  return scatter_impl(dst, index, rows);
}

// fill_scalars(dst_1d, values_list): dst[i] = values[i] for int32/float32
// destinations, accepting Python ints/floats — one C call replaces a
// Python loop of P scalar __setitem__ dispatches.
PyObject* fill_scalars(PyObject*, PyObject* args) {
  PyObject* dst_obj;
  PyObject* vals_obj;
  if (!PyArg_ParseTuple(args, "OO", &dst_obj, &vals_obj)) return nullptr;
  View dst;
  if (!dst.acquire(dst_obj,
                   PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT)) {
    return nullptr;
  }
  if (dst.buf.ndim != 1) {
    PyErr_SetString(PyExc_ValueError, "dst must be 1-D");
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(vals_obj, "values must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (n > dst.buf.shape[0]) n = dst.buf.shape[0];
  const Py_ssize_t isz = dst.buf.itemsize;
  char* base = static_cast<char*>(dst.buf.buf);
  const char kind = dst.buf.format ? dst.buf.format[0] : 'i';
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* v = PySequence_Fast_GET_ITEM(seq, i);
    if (kind == 'f' && isz == 4) {
      const double d = PyFloat_AsDouble(v);
      if (d == -1.0 && PyErr_Occurred()) {
        Py_DECREF(seq);
        return nullptr;
      }
      reinterpret_cast<float*>(base)[i] = static_cast<float>(d);
    } else if (isz == 4) {
      const long x = PyLong_AsLong(v);
      if (x == -1 && PyErr_Occurred()) {
        Py_DECREF(seq);
        return nullptr;
      }
      reinterpret_cast<int*>(base)[i] = static_cast<int>(x);
    } else if (isz == 1) {
      const int t = PyObject_IsTrue(v);
      if (t < 0) {
        Py_DECREF(seq);
        return nullptr;
      }
      base[i] = static_cast<char>(t);
    } else {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_ValueError, "unsupported dst dtype");
      return nullptr;
    }
  }
  Py_DECREF(seq);
  Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"scatter_rows", scatter_rows, METH_VARARGS,
     "scatter_rows(dst2d, rows): dst[i, :len(rows[i])] = rows[i]"},
    {"scatter_rows_at", scatter_rows_at, METH_VARARGS,
     "scatter_rows_at(dst2d, index_i64, rows): dst[index[i], :] = rows[i]"},
    {"fill_scalars", fill_scalars, METH_VARARGS,
     "fill_scalars(dst1d, values): dst[i] = values[i]"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_fastassemble",
    "native snapshot-row assembly (see fastassemble.cc)", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__fastassemble(void) {
  return PyModule_Create(&module);
}
