// Native snapshot-row assembly (SURVEY.md §2 C-notes: "C++ encoder if
// Python encoding becomes the bottleneck" — it did: the per-pod Python
// array writes dominate steady-state re-encode).
//
// One exported function per access pattern, CPython C API + the buffer
// protocol only (no pybind11 in this image):
//
//   scatter_rows(dst, rows, width)
//       dst: 2-D C-contiguous numpy array [R, W_dst]
//       rows: list of 1-D arrays (same dtype), row i copied into
//             dst[i, :len(rows[i])]; rows beyond width are truncated.
//   scatter_rows_at(dst, index, rows)
//       like scatter_rows but row i goes to dst[index[i], :].
//
// The Python encoder falls back to per-row numpy writes when this
// module isn't built (k8s_scheduler_tpu/native/__init__.py), so the
// extension is an accelerator, not a dependency. Build: `make -C
// k8s_scheduler_tpu/native` (or setup.py build_ext --inplace).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstring>
#include <utility>
#include <vector>

namespace {

struct View {
  Py_buffer buf{};
  bool ok = false;
  ~View() {
    if (ok) PyBuffer_Release(&buf);
  }
  bool acquire(PyObject* obj, int flags) {
    if (PyObject_GetBuffer(obj, &buf, flags) != 0) return false;
    ok = true;
    return true;
  }
};

// dst[i or index[i], :len(row_i)] = row_i for every row in `rows`.
PyObject* scatter_impl(PyObject* dst_obj, PyObject* index_obj,
                       PyObject* rows_obj) {
  View dst;
  if (!dst.acquire(dst_obj,
                   PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT)) {
    return nullptr;
  }
  if (dst.buf.ndim != 2) {
    PyErr_SetString(PyExc_ValueError, "dst must be 2-D");
    return nullptr;
  }
  const Py_ssize_t n_rows = dst.buf.shape[0];
  const Py_ssize_t width_bytes = dst.buf.shape[1] * dst.buf.itemsize;
  char* base = static_cast<char*>(dst.buf.buf);

  View index;
  const long* idx = nullptr;
  Py_ssize_t n_idx = 0;
  if (index_obj != nullptr && index_obj != Py_None) {
    if (!index.acquire(index_obj, PyBUF_C_CONTIGUOUS)) return nullptr;
    if (index.buf.ndim != 1 || index.buf.itemsize != sizeof(long)) {
      PyErr_SetString(PyExc_ValueError, "index must be 1-D int64");
      return nullptr;
    }
    idx = static_cast<const long*>(index.buf.buf);
    n_idx = index.buf.shape[0];
  }

  PyObject* seq = PySequence_Fast(rows_obj, "rows must be a sequence");
  if (seq == nullptr) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (idx != nullptr && n > n_idx) {
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError, "index shorter than rows");
    return nullptr;
  }

  // dst element kind for the plain-python-sequence row path
  // (native pod_row emits rows as Python lists, not numpy arrays)
  const char kind = dst.buf.format ? dst.buf.format[0] : 'i';

  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* row = PySequence_Fast_GET_ITEM(seq, i);  // borrowed
    if (row == Py_None) continue;
    View rv;
    if (rv.acquire(row, PyBUF_C_CONTIGUOUS)) {
      if (rv.buf.itemsize != dst.buf.itemsize) {
        Py_DECREF(seq);
        PyErr_Format(PyExc_ValueError,
                     "row %zd itemsize %zd != dst itemsize %zd", i,
                     rv.buf.itemsize, dst.buf.itemsize);
        return nullptr;
      }
      const Py_ssize_t target = idx ? idx[i] : i;
      if (target < 0 || target >= n_rows) {
        Py_DECREF(seq);
        PyErr_Format(PyExc_IndexError, "row %zd target %zd out of range", i,
                     target);
        return nullptr;
      }
      Py_ssize_t bytes = rv.buf.len;
      if (bytes > width_bytes) bytes = width_bytes;  // truncate to dst width
      std::memcpy(base + target * width_bytes, rv.buf.buf,
                  static_cast<size_t>(bytes));
      continue;
    }
    // not a buffer: accept a plain sequence of numbers
    PyErr_Clear();
    PyObject* rseq = PySequence_Fast(row, "row must be buffer or sequence");
    if (rseq == nullptr) {
      Py_DECREF(seq);
      return nullptr;
    }
    const Py_ssize_t target = idx ? idx[i] : i;
    if (target < 0 || target >= n_rows) {
      Py_DECREF(rseq);
      Py_DECREF(seq);
      PyErr_Format(PyExc_IndexError, "row %zd target %zd out of range", i,
                   target);
      return nullptr;
    }
    Py_ssize_t m = PySequence_Fast_GET_SIZE(rseq);
    if (m * dst.buf.itemsize > width_bytes) m = width_bytes / dst.buf.itemsize;
    char* out = base + target * width_bytes;
    for (Py_ssize_t j = 0; j < m; ++j) {
      PyObject* v = PySequence_Fast_GET_ITEM(rseq, j);
      if (kind == 'f' && dst.buf.itemsize == 4) {
        const double d = PyFloat_AsDouble(v);
        if (d == -1.0 && PyErr_Occurred()) {
          Py_DECREF(rseq);
          Py_DECREF(seq);
          return nullptr;
        }
        reinterpret_cast<float*>(out)[j] = static_cast<float>(d);
      } else if (dst.buf.itemsize == 4) {
        const long x = PyLong_AsLong(v);
        if (x == -1 && PyErr_Occurred()) {
          Py_DECREF(rseq);
          Py_DECREF(seq);
          return nullptr;
        }
        reinterpret_cast<int*>(out)[j] = static_cast<int>(x);
      } else {
        Py_DECREF(rseq);
        Py_DECREF(seq);
        PyErr_SetString(PyExc_ValueError, "unsupported dst dtype for list row");
        return nullptr;
      }
    }
    Py_DECREF(rseq);
  }
  Py_DECREF(seq);
  Py_RETURN_NONE;
}

PyObject* scatter_rows(PyObject*, PyObject* args) {
  PyObject* dst;
  PyObject* rows;
  if (!PyArg_ParseTuple(args, "OO", &dst, &rows)) return nullptr;
  return scatter_impl(dst, nullptr, rows);
}

PyObject* scatter_rows_at(PyObject*, PyObject* args) {
  PyObject* dst;
  PyObject* index;
  PyObject* rows;
  if (!PyArg_ParseTuple(args, "OOO", &dst, &index, &rows)) return nullptr;
  return scatter_impl(dst, index, rows);
}

// fill_scalars(dst_1d, values_list): dst[i] = values[i] for int32/float32
// destinations, accepting Python ints/floats — one C call replaces a
// Python loop of P scalar __setitem__ dispatches.
PyObject* fill_scalars(PyObject*, PyObject* args) {
  PyObject* dst_obj;
  PyObject* vals_obj;
  if (!PyArg_ParseTuple(args, "OO", &dst_obj, &vals_obj)) return nullptr;
  View dst;
  if (!dst.acquire(dst_obj,
                   PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT)) {
    return nullptr;
  }
  if (dst.buf.ndim != 1) {
    PyErr_SetString(PyExc_ValueError, "dst must be 1-D");
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(vals_obj, "values must be a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  if (n > dst.buf.shape[0]) n = dst.buf.shape[0];
  const Py_ssize_t isz = dst.buf.itemsize;
  char* base = static_cast<char*>(dst.buf.buf);
  const char kind = dst.buf.format ? dst.buf.format[0] : 'i';
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* v = PySequence_Fast_GET_ITEM(seq, i);
    if (kind == 'f' && isz == 4) {
      const double d = PyFloat_AsDouble(v);
      if (d == -1.0 && PyErr_Occurred()) {
        Py_DECREF(seq);
        return nullptr;
      }
      reinterpret_cast<float*>(base)[i] = static_cast<float>(d);
    } else if (isz == 4) {
      const long x = PyLong_AsLong(v);
      if (x == -1 && PyErr_Occurred()) {
        Py_DECREF(seq);
        return nullptr;
      }
      reinterpret_cast<int*>(base)[i] = static_cast<int>(x);
    } else if (isz == 1) {
      const int t = PyObject_IsTrue(v);
      if (t < 0) {
        Py_DECREF(seq);
        return nullptr;
      }
      base[i] = static_cast<char>(t);
    } else {
      Py_DECREF(seq);
      PyErr_SetString(PyExc_ValueError, "unsupported dst dtype");
      return nullptr;
    }
  }
  Py_DECREF(seq);
  Py_RETURN_NONE;
}

// ---------------------------------------------------------------------------
// pod_row(pod, ctx) -> dict | None
//
// Native fast path for SnapshotEncoder.pod_rowdata (the per-fresh-pod
// Python walk is the steady-state encode bottleneck: ~18us/pod in
// Python, ~3-5us here). The ctx dict hands in the encoder's PERSISTENT
// interning structures (string/expr/selector/toleration/requirement/
// imageset tables as {index: dict, rows: list} pairs, plus id/index
// mirrors), and this function grows them with EXACTLY the same keys the
// Python path would, so both paths are interchangeable per pod.
//
// Returns None (not an error) for pods using features the native path
// does not cover — real nodeAffinity blocks, volumes, or selector
// operators beyond In/NotIn/Exists/DoesNotExist — and the Python path
// handles those pods. Differentially tested against the Python rows.
// ---------------------------------------------------------------------------

struct Ctx {
  PyObject *str_ids, *str_list;          // StringInterner internals
  PyObject *exprs_idx, *exprs_rows;      // expression table
  PyObject *sels_idx, *sels_rows;        // selector table
  PyObject *reqs_idx, *reqs_rows;        // requirement table
  PyObject *tols_idx, *tols_rows;        // toleration-set table
  PyObject *imgsets_idx, *imgsets_rows;  // image-set table
  PyObject *image_ids;                   // image name -> id
  PyObject *group_ids;                   // group name -> id
  PyObject *topo_idx, *topo_list;        // topology keys
  PyObject *rn_idx, *rn_list;            // resource names
  PyObject *ns_key;                      // "__namespace__"
  PyObject *pods_name;                   // "pods"
  long op_in, op_not_in, op_exists, op_dne;
  long tol_eq, tol_exists;
  long when_dns, when_sa;
  PyObject *effect_codes;                // effect str -> int dict
};

// Every name passed to ctx_get/getattr_b is a C string LITERAL, so its
// address is a stable key: intern the unicode object once per literal
// instead of rebuilding it per call (PyObject_GetAttrString /
// PyDict_GetItemString allocate a fresh unicode every time — at ~45
// getattrs + 24 ctx lookups per pod row that was several µs/pod).
// GIL-protected like every other C-API call here.
static PyObject* interned_name(const char* name) {
  // growable, never evicts (advisor r4: the old fixed CAP=128 leaked a
  // ref per call once full — and decref'ing a fresh MORTAL-interned
  // string before the borrowed use would be a use-after-free). The key
  // set is bounded at compile time by the number of distinct C literal
  // call sites in this file, so process-lifetime refs are the contract.
  static std::vector<std::pair<const char*, PyObject*>> cache;
  for (auto& kv : cache) {
    if (kv.first == name) return kv.second;
  }
  PyObject* u = PyUnicode_InternFromString(name);
  if (u) cache.emplace_back(name, u);  // holds the ref for process lifetime
  return u;
}

static bool ctx_get(PyObject* d, const char* k, PyObject** out) {
  PyObject* key = interned_name(k);
  *out = key ? PyDict_GetItemWithError(d, key) : nullptr;  // borrowed
  if (*out == nullptr) {
    if (!PyErr_Occurred()) {
      PyErr_Format(PyExc_KeyError, "pod_row ctx missing %s", k);
    }
    return false;
  }
  return true;
}

static bool ctx_long(PyObject* d, const char* k, long* out) {
  PyObject* v;
  if (!ctx_get(d, k, &v)) return false;
  *out = PyLong_AsLong(v);
  return !(*out == -1 && PyErr_Occurred());
}

// str -> dense id, growing the interner (mirrors StringInterner.intern)
static long intern_str(const Ctx& c, PyObject* s) {
  PyObject* hit = PyDict_GetItemWithError(c.str_ids, s);
  if (hit != nullptr) return PyLong_AsLong(hit);
  if (PyErr_Occurred()) return -2;
  const long n = static_cast<long>(PyList_GET_SIZE(c.str_list));
  PyObject* num = PyLong_FromLong(n);
  if (num == nullptr) return -2;
  if (PyDict_SetItem(c.str_ids, s, num) != 0 ||
      PyList_Append(c.str_list, s) != 0) {
    Py_DECREF(num);
    return -2;
  }
  Py_DECREF(num);
  return n;
}

// hashable row -> dense index, growing the table (mirrors _InternTable)
// steals nothing; `row` is borrowed
static long intern_row(PyObject* idx, PyObject* rows, PyObject* row) {
  PyObject* hit = PyDict_GetItemWithError(idx, row);
  if (hit != nullptr) return PyLong_AsLong(hit);
  if (PyErr_Occurred()) return -2;
  const long n = static_cast<long>(PyList_GET_SIZE(rows));
  PyObject* num = PyLong_FromLong(n);
  if (num == nullptr) return -2;
  if (PyDict_SetItem(idx, row, num) != 0 || PyList_Append(rows, row) != 0) {
    Py_DECREF(num);
    return -2;
  }
  Py_DECREF(num);
  return n;
}

// intern (key, op, (vals...), num) into the expression table
static long intern_expr(const Ctx& c, long key, long op, PyObject* vals,
                        double num) {
  PyObject* row = Py_BuildValue("(llOd)", key, op, vals, num);
  if (row == nullptr) return -2;
  const long r = intern_row(c.exprs_idx, c.exprs_rows, row);
  Py_DECREF(row);
  return r;
}

static PyObject* getattr_b(PyObject* o, const char* name) {
  PyObject* key = interned_name(name);
  return key ? PyObject_GetAttr(o, key) : nullptr;  // new ref
}

// compile a LabelSelector + namespaces -> selector id; -2 on error,
// -3 on unsupported operator (caller falls back)
static long compile_selector(const Ctx& c, PyObject* sel, PyObject* ns) {
  long ns_id = intern_str(c, ns);
  if (ns_id < 0) return -2;
  PyObject* exprs = PyList_New(0);
  if (!exprs) return -2;
  long ns_key_id = intern_str(c, c.ns_key);
  PyObject* vals = Py_BuildValue("(l)", ns_id);
  long e = vals ? intern_expr(c, ns_key_id, c.op_in, vals, 0.0) : -2;
  Py_XDECREF(vals);
  long status = 0;
  PyObject* ml = nullptr;
  PyObject* items = nullptr;
  PyObject* mex = nullptr;
  do {
    if (e < 0) { status = -2; break; }
    PyObject* en = PyLong_FromLong(e);
    if (!en || PyList_Append(exprs, en) != 0) { Py_XDECREF(en); status = -2; break; }
    Py_DECREF(en);
    ml = getattr_b(sel, "match_labels");
    if (!ml) { status = -2; break; }
    items = PyDict_Items(ml);
    if (!items || PyList_Sort(items) != 0) { status = -2; break; }
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(items); ++i) {
      PyObject* kv = PyList_GET_ITEM(items, i);
      long k = intern_str(c, PyTuple_GET_ITEM(kv, 0));
      long v = intern_str(c, PyTuple_GET_ITEM(kv, 1));
      if (k < 0 || v < 0) { status = -2; break; }
      PyObject* vv = Py_BuildValue("(l)", v);
      long ei = vv ? intern_expr(c, k, c.op_in, vv, 0.0) : -2;
      Py_XDECREF(vv);
      if (ei < 0) { status = -2; break; }
      PyObject* eo = PyLong_FromLong(ei);
      if (!eo || PyList_Append(exprs, eo) != 0) { Py_XDECREF(eo); status = -2; break; }
      Py_DECREF(eo);
    }
    if (status) break;
    mex = getattr_b(sel, "match_expressions");
    if (!mex) { status = -2; break; }
    PyObject* mseq = PySequence_Fast(mex, "match_expressions");
    if (!mseq) { status = -2; break; }
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(mseq); ++i) {
      PyObject* r = PySequence_Fast_GET_ITEM(mseq, i);
      PyObject* opo = getattr_b(r, "operator");
      PyObject* keyo = getattr_b(r, "key");
      PyObject* valso = getattr_b(r, "values");
      if (!opo || !keyo || !valso) {
        Py_XDECREF(opo); Py_XDECREF(keyo); Py_XDECREF(valso);
        status = -2; break;
      }
      long op = -1;
      const char* ops = PyUnicode_AsUTF8(opo);
      if (ops == nullptr) { status = -2; }
      else if (!strcmp(ops, "In")) op = c.op_in;
      else if (!strcmp(ops, "NotIn")) op = c.op_not_in;
      else if (!strcmp(ops, "Exists")) op = c.op_exists;
      else if (!strcmp(ops, "DoesNotExist")) op = c.op_dne;
      else status = -3;  // Gt/Lt on pod selectors: fall back
      long ei = -2;
      if (!status) {
        PyObject* vseq = PySequence_Fast(valso, "values");
        if (!vseq) { status = -2; }
        else {
          const Py_ssize_t nv = PySequence_Fast_GET_SIZE(vseq);
          PyObject* ids = PyList_New(0);
          if (!ids) status = -2;
          for (Py_ssize_t j = 0; !status && j < nv; ++j) {
            long vid = intern_str(c, PySequence_Fast_GET_ITEM(vseq, j));
            if (vid < 0) { status = -2; break; }
            PyObject* vo = PyLong_FromLong(vid);
            if (!vo || PyList_Append(ids, vo) != 0) { Py_XDECREF(vo); status = -2; break; }
            Py_DECREF(vo);
          }
          if (!status) {
            if (PyList_Sort(ids) != 0) status = -2;
          }
          if (!status) {
            // key interned AFTER the values (Python evaluation order)
            long k = intern_str(c, keyo);
            PyObject* vt = (k >= 0) ? PyList_AsTuple(ids) : nullptr;
            if (!vt) status = -2;
            else {
              ei = intern_expr(c, k, op, vt, 0.0);
              Py_DECREF(vt);
              if (ei < 0) status = -2;
            }
          }
          Py_XDECREF(ids);
        }
        Py_XDECREF(vseq);
      }
      Py_DECREF(opo); Py_DECREF(keyo); Py_DECREF(valso);
      if (status) break;
      PyObject* eo = PyLong_FromLong(ei);
      if (!eo || PyList_Append(exprs, eo) != 0) { Py_XDECREF(eo); status = -2; break; }
      Py_DECREF(eo);
    }
    Py_DECREF(mseq);
  } while (false);
  Py_XDECREF(ml); Py_XDECREF(items); Py_XDECREF(mex);
  long out = status;
  if (!status) {
    PyObject* t = PyList_AsTuple(exprs);
    out = t ? intern_row(c.sels_idx, c.sels_rows, t) : -2;
    Py_XDECREF(t);
  }
  Py_DECREF(exprs);
  return out;
}

static long topo_key_id(const Ctx& c, PyObject* key) {
  PyObject* hit = PyDict_GetItemWithError(c.topo_idx, key);
  if (hit != nullptr) return PyLong_AsLong(hit);
  if (PyErr_Occurred()) return -2;
  const long n = static_cast<long>(PyList_GET_SIZE(c.topo_list));
  PyObject* num = PyLong_FromLong(n);
  if (!num) return -2;
  if (PyDict_SetItem(c.topo_idx, key, num) != 0 ||
      PyList_Append(c.topo_list, key) != 0) {
    Py_DECREF(num);
    return -2;
  }
  Py_DECREF(num);
  return n;
}

// append a long to a Python list; true on success
static bool lappend(PyObject* lst, long v) {
  PyObject* o = PyLong_FromLong(v);
  if (!o) return false;
  const bool ok = PyList_Append(lst, o) == 0;
  Py_DECREF(o);
  return ok;
}

static bool lappendf(PyObject* lst, double v) {
  PyObject* o = PyFloat_FromDouble(v);
  if (!o) return false;
  const bool ok = PyList_Append(lst, o) == 0;
  Py_DECREF(o);
  return ok;
}

// compile pod-affinity terms into (sel, topo) pairs appended FLAT to
// `flat`; returns term count, -2 error, -3 unsupported
static long compile_aff_terms(const Ctx& c, PyObject* terms, PyObject* ns,
                              std::vector<long>& flat) {
  PyObject* seq = PySequence_Fast(terms, "terms");
  if (!seq) return -2;
  long count = 0;
  long status = 0;
  for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); ++i) {
    PyObject* t = PySequence_Fast_GET_ITEM(seq, i);
    PyObject* nss = getattr_b(t, "namespaces");
    if (!nss) { status = -2; break; }
    bool has_ns = PyObject_IsTrue(nss) == 1;
    if (has_ns) {
      // multi-namespace terms: supported only for a single namespace
      // equal to... keep simple: fall back
      Py_DECREF(nss);
      status = -3;
      break;
    }
    Py_DECREF(nss);
    PyObject* ls = getattr_b(t, "label_selector");
    PyObject* tk = getattr_b(t, "topology_key");
    if (!ls || !tk) { Py_XDECREF(ls); Py_XDECREF(tk); status = -2; break; }
    long sid = compile_selector(c, ls, ns);
    long kid = (sid >= 0) ? topo_key_id(c, tk) : -1;
    Py_DECREF(ls); Py_DECREF(tk);
    if (sid == -3) { status = -3; break; }
    if (sid < 0 || kid < 0) { status = -2; break; }
    flat.push_back(sid);
    flat.push_back(kid);
    ++count;
  }
  Py_DECREF(seq);
  return status ? status : count;
}

// ---------------------------------------------------------------------------
// Parsed: one pod row as plain C data. parse_pod fills it in a single
// attribute walk; pod_row boxes it into the rowdata dict (fallback /
// full-path interchange), while pod_rows_into (the delta fast path)
// writes it straight into the arena with no Python containers at all
// (PERF.md round-4 close-out: the dict build + apply re-read were ~39
// of the ~45 ms warm encode at config #4).
// ---------------------------------------------------------------------------
struct Parsed {
  std::vector<double> reqvec;
  std::vector<long> lab_k, lab_v, ports, aff, anti, pref, tsc, tsc_skew;
  std::vector<double> pref_w;
  long prio = 0, sel_req_id = -1, tolset = -1, gid = -1, imageset = -1,
       n_aff = 0;
  bool can_preempt = true;
  double creation = 0.0;
};

static PyObject* list_from(const std::vector<long>& v) {
  PyObject* l = PyList_New(static_cast<Py_ssize_t>(v.size()));
  if (!l) return nullptr;
  for (size_t i = 0; i < v.size(); ++i) {
    PyObject* o = PyLong_FromLong(v[i]);
    if (!o) { Py_DECREF(l); return nullptr; }
    PyList_SET_ITEM(l, static_cast<Py_ssize_t>(i), o);
  }
  return l;
}

static PyObject* list_fromf(const std::vector<double>& v) {
  PyObject* l = PyList_New(static_cast<Py_ssize_t>(v.size()));
  if (!l) return nullptr;
  for (size_t i = 0; i < v.size(); ++i) {
    PyObject* o = PyFloat_FromDouble(v[i]);
    if (!o) { Py_DECREF(l); return nullptr; }
    PyList_SET_ITEM(l, static_cast<Py_ssize_t>(i), o);
  }
  return l;
}

static bool load_ctx(PyObject* ctxd, Ctx& c) {
  if (!ctx_get(ctxd, "str_ids", &c.str_ids) ||
      !ctx_get(ctxd, "str_list", &c.str_list) ||
      !ctx_get(ctxd, "exprs_idx", &c.exprs_idx) ||
      !ctx_get(ctxd, "exprs_rows", &c.exprs_rows) ||
      !ctx_get(ctxd, "sels_idx", &c.sels_idx) ||
      !ctx_get(ctxd, "sels_rows", &c.sels_rows) ||
      !ctx_get(ctxd, "reqs_idx", &c.reqs_idx) ||
      !ctx_get(ctxd, "reqs_rows", &c.reqs_rows) ||
      !ctx_get(ctxd, "tols_idx", &c.tols_idx) ||
      !ctx_get(ctxd, "tols_rows", &c.tols_rows) ||
      !ctx_get(ctxd, "imgsets_idx", &c.imgsets_idx) ||
      !ctx_get(ctxd, "imgsets_rows", &c.imgsets_rows) ||
      !ctx_get(ctxd, "image_ids", &c.image_ids) ||
      !ctx_get(ctxd, "group_ids", &c.group_ids) ||
      !ctx_get(ctxd, "topo_idx", &c.topo_idx) ||
      !ctx_get(ctxd, "topo_list", &c.topo_list) ||
      !ctx_get(ctxd, "rn_idx", &c.rn_idx) ||
      !ctx_get(ctxd, "rn_list", &c.rn_list) ||
      !ctx_get(ctxd, "ns_key", &c.ns_key) ||
      !ctx_get(ctxd, "pods_name", &c.pods_name) ||
      !ctx_get(ctxd, "effect_codes", &c.effect_codes) ||
      !ctx_long(ctxd, "op_in", &c.op_in) ||
      !ctx_long(ctxd, "op_not_in", &c.op_not_in) ||
      !ctx_long(ctxd, "op_exists", &c.op_exists) ||
      !ctx_long(ctxd, "op_dne", &c.op_dne) ||
      !ctx_long(ctxd, "tol_eq", &c.tol_eq) ||
      !ctx_long(ctxd, "tol_exists", &c.tol_exists) ||
      !ctx_long(ctxd, "when_dns", &c.when_dns) ||
      !ctx_long(ctxd, "when_sa", &c.when_sa)) {
    return false;
  }
  return true;
}

// Parse one pod into `P`. Returns 0 ok, -2 error (Python error set),
// -3 unsupported feature (caller falls back to the Python rowdata path).
static long parse_pod(const Ctx& c, PyObject* pod, Parsed& P) {
  PyObject *spec = nullptr, *meta = nullptr;
  PyObject* image_names = nullptr;  // strong-ref image name objects
  long status = 0;  // 0 ok, -2 error, -3 fallback

  do {
    spec = getattr_b(pod, "spec");
    meta = getattr_b(pod, "metadata");
    if (!spec || !meta) { status = -2; break; }

    // ---- fallbacks first (cheap attribute probes) ----
    PyObject* vols = getattr_b(spec, "volumes");
    if (!vols) { status = -2; break; }
    const bool has_vols = PyObject_IsTrue(vols) == 1;
    Py_DECREF(vols);
    if (has_vols) { status = -3; break; }
    PyObject* affin = getattr_b(spec, "affinity");
    if (!affin) { status = -2; break; }
    PyObject *pa = nullptr, *paa = nullptr;
    if (affin != Py_None) {
      PyObject* na = getattr_b(affin, "node_affinity");
      if (!na) { Py_DECREF(affin); status = -2; break; }
      const bool has_na = na != Py_None;
      Py_DECREF(na);
      if (has_na) { Py_DECREF(affin); status = -3; break; }
      pa = getattr_b(affin, "pod_affinity");
      paa = getattr_b(affin, "pod_anti_affinity");
      if (!pa || !paa) {
        Py_XDECREF(pa); Py_XDECREF(paa); Py_DECREF(affin);
        status = -2; break;
      }
    }
    Py_DECREF(affin);

    PyObject* ns = getattr_b(pod, "namespace");
    if (!ns) { Py_XDECREF(pa); Py_XDECREF(paa); status = -2; break; }

    // ---- node_selector -> sel_req_id ----
    {
      PyObject* nsel = getattr_b(spec, "node_selector");
      if (!nsel) status = -2;
      else if (PyObject_IsTrue(nsel) == 1) {
        PyObject* items = PyDict_Items(nsel);
        if (!items || PyList_Sort(items) != 0) status = -2;
        PyObject* exprs = status ? nullptr : PyList_New(0);
        if (!status && !exprs) status = -2;
        for (Py_ssize_t i = 0; !status && i < PyList_GET_SIZE(items); ++i) {
          PyObject* kv = PyList_GET_ITEM(items, i);
          // Python's compile_req interns VALUES before the key
          long v = intern_str(c, PyTuple_GET_ITEM(kv, 1));
          long k = intern_str(c, PyTuple_GET_ITEM(kv, 0));
          if (k < 0 || v < 0) { status = -2; break; }
          PyObject* vt = Py_BuildValue("(l)", v);
          long e = vt ? intern_expr(c, k, c.op_in, vt, 0.0) : -2;
          Py_XDECREF(vt);
          if (e < 0 || !lappend(exprs, e)) { status = -2; break; }
        }
        if (!status) {
          PyObject* et = PyList_AsTuple(exprs);
          PyObject* terms = et ? Py_BuildValue("(O)", et) : nullptr;
          if (!terms) status = -2;
          else {
            P.sel_req_id = intern_row(c.reqs_idx, c.reqs_rows, terms);
            if (P.sel_req_id < 0) status = -2;
            Py_DECREF(terms);
          }
          Py_XDECREF(et);
        }
        Py_XDECREF(exprs);
        Py_XDECREF(items);
      }
      Py_XDECREF(nsel);
    }
    if (status) { Py_XDECREF(pa); Py_XDECREF(paa); Py_DECREF(ns); break; }

    // ---- pod (anti-)affinity ----
    long n_aff_terms = 0, n_anti_terms = 0, n_pref_terms = 0;
    // preferred terms of BOTH polarities land flat in P.pref with a
    // signed weight in P.pref_w (anti-affinity preference = -w)
    for (int pol = 0; !status && pol < 2; ++pol) {
      PyObject* src = pol == 0 ? pa : paa;
      if (!src || src == Py_None) continue;
      PyObject* reqt = getattr_b(src, "required");
      long n1 = reqt ? compile_aff_terms(c, reqt, ns, pol == 0 ? P.aff : P.anti)
                     : -2;
      Py_XDECREF(reqt);
      if (n1 < 0) { status = n1; break; }
      (pol == 0 ? n_aff_terms : n_anti_terms) = n1;
      PyObject* pt = getattr_b(src, "preferred");
      PyObject* seq = pt ? PySequence_Fast(pt, "preferred") : nullptr;
      if (!seq) status = -2;
      for (Py_ssize_t i = 0;
           !status && seq && i < PySequence_Fast_GET_SIZE(seq); ++i) {
        PyObject* wt = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* term = getattr_b(wt, "term");
        PyObject* w = getattr_b(wt, "weight");
        if (!term || !w) status = -2;
        if (!status) {
          std::vector<long> one;
          PyObject* tt = PyTuple_Pack(1, term);
          long n2 = tt ? compile_aff_terms(c, tt, ns, one) : -2;
          Py_XDECREF(tt);
          if (n2 < 0) status = n2;
          else {
            const double wv = PyFloat_AsDouble(w);
            if (wv == -1.0 && PyErr_Occurred()) status = -2;
            else if (one.size() >= 2) {
              P.pref.push_back(one[0]);
              P.pref.push_back(one[1]);
              P.pref_w.push_back(pol == 0 ? wv : -wv);
              ++n_pref_terms;
            }
          }
        }
        Py_XDECREF(term); Py_XDECREF(w);
      }
      Py_XDECREF(seq); Py_XDECREF(pt);
    }
    Py_XDECREF(pa); Py_XDECREF(paa);
    pa = paa = nullptr;
    if (status) { Py_DECREF(ns); break; }

    // ---- topology spread constraints ----
    {
      PyObject* tscs = getattr_b(spec, "topology_spread_constraints");
      PyObject* seq = tscs ? PySequence_Fast(tscs, "tsc") : nullptr;
      if (!seq) status = -2;
      for (Py_ssize_t i = 0;
           !status && seq && i < PySequence_Fast_GET_SIZE(seq); ++i) {
        PyObject* cns = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* tk = getattr_b(cns, "topology_key");
        PyObject* ls = getattr_b(cns, "label_selector");
        PyObject* wu = getattr_b(cns, "when_unsatisfiable");
        PyObject* sk = getattr_b(cns, "max_skew");
        if (!tk || !ls || !wu || !sk) status = -2;
        if (!status) {
          long kid = topo_key_id(c, tk);
          long sid = compile_selector(c, ls, ns);
          if (sid == -3) status = -3;
          else if (kid < 0 || sid < 0) status = -2;
          else {
            const char* wus = PyUnicode_AsUTF8(wu);
            long when = (wus && !strcmp(wus, "DoNotSchedule")) ? c.when_dns
                                                               : c.when_sa;
            const long skew = PyLong_AsLong(sk);
            if (skew == -1 && PyErr_Occurred()) status = -2;
            else {
              P.tsc.push_back(kid);
              P.tsc.push_back(sid);
              P.tsc.push_back(when);
              P.tsc_skew.push_back(skew);
            }
          }
        }
        Py_XDECREF(tk); Py_XDECREF(ls); Py_XDECREF(wu); Py_XDECREF(sk);
      }
      Py_XDECREF(seq); Py_XDECREF(tscs);
    }
    if (status) { Py_DECREF(ns); break; }

    // ---- labels (namespace marker first, then sorted) ----
    {
      long nk = intern_str(c, c.ns_key);
      long nv = intern_str(c, ns);
      if (nk < 0 || nv < 0) status = -2;
      else {
        P.lab_k.push_back(nk);
        P.lab_v.push_back(nv);
      }
    }
    if (!status) {
      PyObject* labels = getattr_b(meta, "labels");
      PyObject* items = labels ? PyDict_Items(labels) : nullptr;
      if (!items || PyList_Sort(items) != 0) status = -2;
      for (Py_ssize_t i = 0; !status && items && i < PyList_GET_SIZE(items);
           ++i) {
        PyObject* kv = PyList_GET_ITEM(items, i);
        long k = intern_str(c, PyTuple_GET_ITEM(kv, 0));
        long v = intern_str(c, PyTuple_GET_ITEM(kv, 1));
        if (k < 0 || v < 0) status = -2;
        else {
          P.lab_k.push_back(k);
          P.lab_v.push_back(v);
        }
      }
      Py_XDECREF(items);
      Py_XDECREF(labels);
    }
    if (status) { Py_DECREF(ns); break; }

    // ---- requests -> reqvec (grow rn as needed), plus ports/images
    // collected in the same container walk (mirrors
    // Pod.resource_requests/host_ports/images without re-entering
    // Python bytecode per pod) ----
    image_names = PyList_New(0);
    {
      // effective request dict, preserving Python's insertion order
      PyObject* req = PyDict_New();
      PyObject* conts = getattr_b(spec, "containers");
      PyObject* cseq = conts ? PySequence_Fast(conts, "containers") : nullptr;
      if (!req || !image_names || !cseq) status = -2;
      for (Py_ssize_t i = 0;
           !status && cseq && i < PySequence_Fast_GET_SIZE(cseq); ++i) {
        PyObject* ct = PySequence_Fast_GET_ITEM(cseq, i);
        PyObject* creq = getattr_b(ct, "requests");
        if (!creq) { status = -2; break; }
        PyObject *key, *val;
        Py_ssize_t pos = 0;
        while (PyDict_Next(creq, &pos, &key, &val)) {
          PyObject* cur = PyDict_GetItemWithError(req, key);
          const double add = PyFloat_AsDouble(val);
          const double base = cur ? PyFloat_AsDouble(cur) : 0.0;
          PyObject* nv = PyFloat_FromDouble(base + add);
          if (!nv || PyDict_SetItem(req, key, nv) != 0) {
            Py_XDECREF(nv); status = -2; break;
          }
          Py_DECREF(nv);
        }
        Py_DECREF(creq);
        if (status) break;
        PyObject* cports = getattr_b(ct, "ports");
        PyObject* pseq = cports ? PySequence_Fast(cports, "ports") : nullptr;
        if (!pseq) { Py_XDECREF(cports); status = -2; break; }
        for (Py_ssize_t j = 0; j < PySequence_Fast_GET_SIZE(pseq); ++j) {
          PyObject* po = PySequence_Fast_GET_ITEM(pseq, j);
          PyObject* hp = getattr_b(po, "host_port");
          if (!hp) { status = -2; break; }
          const long port = PyLong_AsLong(hp);
          Py_DECREF(hp);
          if (port == 0) continue;
          PyObject* pr = getattr_b(po, "protocol");
          const char* ps = pr ? PyUnicode_AsUTF8(pr) : nullptr;
          long pc = 3;
          if (ps) {
            if (!strcmp(ps, "TCP")) pc = 0;
            else if (!strcmp(ps, "UDP")) pc = 1;
            else if (!strcmp(ps, "SCTP")) pc = 2;
          }
          Py_XDECREF(pr);
          P.ports.push_back(port * 4 + pc);
        }
        Py_DECREF(pseq); Py_DECREF(cports);
        if (status) break;
        PyObject* img = getattr_b(ct, "image");
        if (!img) { status = -2; break; }
        if (PyObject_IsTrue(img) == 1 &&
            PyList_Append(image_names, img) != 0) {
          status = -2;
        }
        Py_DECREF(img);
      }
      Py_XDECREF(cseq); Py_XDECREF(conts);
      if (!status) {
        PyObject* ovh = getattr_b(spec, "overhead");
        if (!ovh) status = -2;
        else {
          PyObject *key, *val;
          Py_ssize_t pos = 0;
          while (PyDict_Next(ovh, &pos, &key, &val)) {
            PyObject* cur = PyDict_GetItemWithError(req, key);
            const double base = cur ? PyFloat_AsDouble(cur) : 0.0;
            PyObject* nv = PyFloat_FromDouble(base + PyFloat_AsDouble(val));
            if (!nv || PyDict_SetItem(req, key, nv) != 0) {
              Py_XDECREF(nv); status = -2; break;
            }
            Py_DECREF(nv);
          }
          Py_DECREF(ovh);
        }
      }
      if (!status) {
        // the implicit one-"pods"-slot request
        PyObject* cur = PyDict_GetItemWithError(req, c.pods_name);
        const double base = cur ? PyFloat_AsDouble(cur) : 0.0;
        PyObject* nv = PyFloat_FromDouble(base + 1.0);
        if (!nv || PyDict_SetItem(req, c.pods_name, nv) != 0) {
          Py_XDECREF(nv); status = -2;
        } else {
          Py_DECREF(nv);
        }
      }
      if (!status) {
        // ensure every name is in rn (insertion order = Python path's)
        PyObject *key, *val;
        Py_ssize_t pos = 0;
        while (!status && PyDict_Next(req, &pos, &key, &val)) {
          if (PyDict_GetItemWithError(c.rn_idx, key) == nullptr) {
            if (PyErr_Occurred()) { status = -2; break; }
            const long n = static_cast<long>(PyList_GET_SIZE(c.rn_list));
            PyObject* num = PyLong_FromLong(n);
            if (!num || PyDict_SetItem(c.rn_idx, key, num) != 0 ||
                PyList_Append(c.rn_list, key) != 0) {
              Py_XDECREF(num); status = -2; break;
            }
            Py_DECREF(num);
          }
        }
        if (!status) {
          const Py_ssize_t R = PyList_GET_SIZE(c.rn_list);
          P.reqvec.assign(static_cast<size_t>(R), 0.0);
          pos = 0;
          while (!status && PyDict_Next(req, &pos, &key, &val)) {
            PyObject* io = PyDict_GetItemWithError(c.rn_idx, key);
            if (!io) { status = -2; break; }
            const long i = PyLong_AsLong(io);
            const double d = PyFloat_AsDouble(val);
            if (d == -1.0 && PyErr_Occurred()) { status = -2; break; }
            P.reqvec[static_cast<size_t>(i)] = d;
          }
        }
      }
      Py_XDECREF(req);
    }
    if (status) { Py_DECREF(ns); break; }

    // ---- tolerations ----
    {
      PyObject* tols = getattr_b(spec, "tolerations");
      PyObject* seq = tols ? PySequence_Fast(tols, "tolerations") : nullptr;
      PyObject* rows = seq ? PyList_New(0) : nullptr;
      if (!seq || !rows) status = -2;
      for (Py_ssize_t i = 0;
           !status && seq && i < PySequence_Fast_GET_SIZE(seq); ++i) {
        PyObject* t = PySequence_Fast_GET_ITEM(seq, i);
        PyObject* keyo = getattr_b(t, "key");
        PyObject* opo = getattr_b(t, "operator");
        PyObject* valo = getattr_b(t, "value");
        PyObject* effo = getattr_b(t, "effect");
        if (!keyo || !opo || !valo || !effo) status = -2;
        if (!status) {
          long key = (PyObject_IsTrue(keyo) == 1) ? intern_str(c, keyo) : -1;
          const char* ops = PyUnicode_AsUTF8(opo);
          long op = (ops && !strcmp(ops, "Exists")) ? c.tol_exists : c.tol_eq;
          long val = intern_str(c, valo);
          long eff = -1;
          if (PyObject_IsTrue(effo) == 1) {
            PyObject* eo = PyDict_GetItemWithError(c.effect_codes, effo);
            if (!eo) { status = -2; }
            else eff = PyLong_AsLong(eo);
          }
          if (key == -2 || val < 0) status = -2;
          if (!status) {
            PyObject* row = Py_BuildValue("(llll)", key, op, val, eff);
            if (!row || PyList_Append(rows, row) != 0) status = -2;
            Py_XDECREF(row);
          }
        }
        Py_XDECREF(keyo); Py_XDECREF(opo); Py_XDECREF(valo); Py_XDECREF(effo);
      }
      if (!status && PyList_Sort(rows) != 0) status = -2;
      if (!status) {
        PyObject* rt = PyList_AsTuple(rows);
        P.tolset = rt ? intern_row(c.tols_idx, c.tols_rows, rt) : -2;
        Py_XDECREF(rt);
        if (P.tolset < 0) status = -2;
      }
      Py_XDECREF(rows); Py_XDECREF(seq); Py_XDECREF(tols);
    }
    if (status) { Py_DECREF(ns); break; }

    // ---- image set, group, scalars (ports/images collected above) ----
    if (!status) {
      PyObject* ids = PyList_New(0);
      if (!ids) status = -2;
      for (Py_ssize_t i = 0;
           !status && ids && i < PyList_GET_SIZE(image_names); ++i) {
        PyObject* nm = PyList_GET_ITEM(image_names, i);
        PyObject* hit = PyDict_GetItemWithError(c.image_ids, nm);
        long iid;
        if (hit) iid = PyLong_AsLong(hit);
        else if (PyErr_Occurred()) { status = -2; break; }
        else {
          iid = static_cast<long>(PyDict_Size(c.image_ids));
          PyObject* num = PyLong_FromLong(iid);
          if (!num || PyDict_SetItem(c.image_ids, nm, num) != 0) {
            Py_XDECREF(num); status = -2; break;
          }
          Py_DECREF(num);
        }
        if (!lappend(ids, iid)) { status = -2; break; }
      }
      if (!status) {
        if (PyList_Sort(ids) != 0) status = -2;
        else {
          PyObject* it = PyList_AsTuple(ids);
          P.imageset =
              it ? intern_row(c.imgsets_idx, c.imgsets_rows, it) : -2;
          Py_XDECREF(it);
          if (P.imageset < 0) status = -2;
        }
      }
      Py_XDECREF(ids);
    }
    if (!status) {
      PyObject* g = getattr_b(spec, "pod_group");
      if (!g) status = -2;
      else if (PyObject_IsTrue(g) == 1) {
        PyObject* hit = PyDict_GetItemWithError(c.group_ids, g);
        if (hit) P.gid = PyLong_AsLong(hit);
        else if (PyErr_Occurred()) status = -2;
        else {
          P.gid = static_cast<long>(PyDict_Size(c.group_ids));
          PyObject* num = PyLong_FromLong(P.gid);
          if (!num || PyDict_SetItem(c.group_ids, g, num) != 0) {
            Py_XDECREF(num); status = -2;
          } else {
            Py_DECREF(num);
          }
        }
      }
      Py_XDECREF(g);
    }
    Py_DECREF(ns);
    if (status) break;

    {
      PyObject* p = getattr_b(spec, "priority");
      PyObject* ct = getattr_b(meta, "creation_timestamp");
      PyObject* pp = getattr_b(spec, "preemption_policy");
      if (!p || !ct || !pp) status = -2;
      else {
        P.prio = PyLong_AsLong(p);
        P.creation = PyFloat_AsDouble(ct);
        const char* pps = PyUnicode_AsUTF8(pp);
        P.can_preempt = !(pps && !strcmp(pps, "Never"));
        if ((P.prio == -1 || P.creation == -1.0) && PyErr_Occurred()) {
          status = -2;
        }
      }
      Py_XDECREF(p); Py_XDECREF(ct); Py_XDECREF(pp);
    }
    if (status) break;

    P.n_aff = n_aff_terms;
    if (n_anti_terms > P.n_aff) P.n_aff = n_anti_terms;
    if (n_pref_terms > P.n_aff) P.n_aff = n_pref_terms;
  } while (false);

  Py_XDECREF(spec); Py_XDECREF(meta);
  Py_XDECREF(image_names);
  return status;
}

PyObject* pod_row(PyObject*, PyObject* args) {
  PyObject *pod, *ctxd;
  if (!PyArg_ParseTuple(args, "OO", &pod, &ctxd)) return nullptr;
  Ctx c{};
  if (!load_ctx(ctxd, c)) return nullptr;
  Parsed P;
  const long status = parse_pod(c, pod, P);
  if (status == -3) {
    PyErr_Clear();
    Py_RETURN_NONE;  // unsupported feature: caller uses the Python path
  }
  if (status) {
    if (!PyErr_Occurred()) {
      PyErr_SetString(PyExc_RuntimeError, "pod_row internal error");
    }
    return nullptr;
  }
  PyObject *reqvec = list_fromf(P.reqvec), *lab_k = list_from(P.lab_k),
           *lab_v = list_from(P.lab_v), *ports = list_from(P.ports),
           *aff = list_from(P.aff), *anti = list_from(P.anti),
           *pref = list_from(P.pref), *pref_w = list_fromf(P.pref_w),
           *tsc = list_from(P.tsc), *tsc_skew = list_from(P.tsc_skew),
           *empty = PyList_New(0);
  PyObject* out = nullptr;
  if (reqvec && lab_k && lab_v && ports && aff && anti && pref && pref_w &&
      tsc && tsc_skew && empty) {
    out = Py_BuildValue(
        "{s:O,s:l,s:d,s:l,s:l,s:l,s:l,s:O,s:O,s:O,s:O,s:O,s:O,s:O,s:O,s:O,"
        "s:l,s:l,s:l,s:O,s:O,s:O,s:O,s:O,s:O,s:O}",
        "reqvec", reqvec, "prio", P.prio, "creation", P.creation,
        "req_id", static_cast<long>(-1), "pref_id", static_cast<long>(-1),
        "sel_req_id", P.sel_req_id, "tolset", P.tolset,
        "lab_k", lab_k, "lab_v", lab_v, "ports", ports,
        "aff", aff, "anti", anti, "pref", pref, "pref_w", pref_w,
        "tsc", tsc, "tsc_skew", tsc_skew,
        "n_aff", P.n_aff, "gid", P.gid, "imageset", P.imageset,
        "can_preempt", P.can_preempt ? Py_True : Py_False,
        "vol_mode", empty, "vol_req", empty, "vol_cls", empty,
        "vol_size", empty, "vol_epoch", Py_None, "epoch", Py_None);
  }
  Py_XDECREF(reqvec); Py_XDECREF(lab_k); Py_XDECREF(lab_v);
  Py_XDECREF(ports); Py_XDECREF(aff); Py_XDECREF(anti); Py_XDECREF(pref);
  Py_XDECREF(pref_w); Py_XDECREF(tsc); Py_XDECREF(tsc_skew);
  Py_XDECREF(empty);
  if (!out && !PyErr_Occurred()) {
    PyErr_SetString(PyExc_RuntimeError, "pod_row internal error");
  }
  return out;
}

// ---------------------------------------------------------------------------
// apply_rows(specs, index_i64, rowdicts)
//
// The delta encoder's whole arena-write pass in one call: `specs` is a
// sequence of (dst_array, key, pad, mode) — mode 0: dst is 2-D, row i
// gets pad-filled then rowdicts[i][key] (a number sequence or buffer)
// written at dst[index[i], :]; mode 1: dst is 1-D and rowdicts[i][key]
// (scalar) lands at dst[index[i]].  Replaces, per field, a numpy
// fancy-index pad fill plus a 2000-element Python list comprehension
// plus a scatter_rows_at call — the per-field Python round trips were
// ~1/4 of the warm delta encode at 10k pods.
PyObject* apply_rows(PyObject*, PyObject* args) {
  PyObject *specs_obj, *index_obj, *rows_obj;
  if (!PyArg_ParseTuple(args, "OOO", &specs_obj, &index_obj, &rows_obj)) {
    return nullptr;
  }
  View index;
  if (!index.acquire(index_obj, PyBUF_C_CONTIGUOUS)) return nullptr;
  if (index.buf.ndim != 1 ||
      index.buf.itemsize != static_cast<Py_ssize_t>(sizeof(long))) {
    PyErr_SetString(PyExc_ValueError, "index must be 1-D int64");
    return nullptr;
  }
  const long* idx = static_cast<const long*>(index.buf.buf);
  const Py_ssize_t n_idx = index.buf.shape[0];

  PyObject* rows = PySequence_Fast(rows_obj, "rows must be a sequence");
  if (!rows) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(rows);
  PyObject* specs = n <= n_idx
                        ? PySequence_Fast(specs_obj, "specs must be a sequence")
                        : nullptr;
  if (!specs) {
    Py_DECREF(rows);
    if (!PyErr_Occurred()) {
      PyErr_SetString(PyExc_ValueError, "index shorter than rows");
    }
    return nullptr;
  }

  bool ok = true;
  for (Py_ssize_t s = 0; ok && s < PySequence_Fast_GET_SIZE(specs); ++s) {
    PyObject* spec = PySequence_Fast_GET_ITEM(specs, s);
    PyObject *dst_obj, *key, *pad_obj;
    long mode = 0;
    {
      PyObject* m = nullptr;
      if (!PyArg_ParseTuple(spec, "OOOO", &dst_obj, &key, &pad_obj, &m)) {
        ok = false;
        break;
      }
      mode = PyLong_AsLong(m);
      if (mode == -1 && PyErr_Occurred()) { ok = false; break; }
    }
    View dst;
    if (!dst.acquire(dst_obj,
                     PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT)) {
      ok = false;
      break;
    }
    const char kind = dst.buf.format ? dst.buf.format[0] : 'i';
    const Py_ssize_t isz = dst.buf.itemsize;
    char* base = static_cast<char*>(dst.buf.buf);
    const Py_ssize_t n_rows_dst = dst.buf.shape[0];

    if (mode == 1) {  // scalar column
      if (dst.buf.ndim != 1) {
        PyErr_SetString(PyExc_ValueError, "mode-1 dst must be 1-D");
        ok = false;
        break;
      }
      for (Py_ssize_t i = 0; ok && i < n; ++i) {
        PyObject* d = PySequence_Fast_GET_ITEM(rows, i);
        PyObject* v = PyDict_GetItemWithError(d, key);  // borrowed
        const Py_ssize_t t = idx[i];
        if (!v || t < 0 || t >= n_rows_dst) {
          if (!PyErr_Occurred()) {
            PyErr_SetString(PyExc_KeyError, "apply_rows: bad key/target");
          }
          ok = false;
          break;
        }
        if (kind == 'f' && isz == 4) {
          const double x = PyFloat_AsDouble(v);
          if (x == -1.0 && PyErr_Occurred()) { ok = false; break; }
          reinterpret_cast<float*>(base)[t] = static_cast<float>(x);
        } else if (isz == 4) {
          // PyLong_AsLong accepts bool directly; anything non-integral
          // (None, str, float) must raise loudly, matching the numpy
          // assignment this replaced — silent 0/1 coercion would feed
          // the scheduler wrong arena values
          const long x = PyLong_AsLong(v);
          if (x == -1 && PyErr_Occurred()) { ok = false; break; }
          reinterpret_cast<int*>(base)[t] = static_cast<int>(x);
        } else if (isz == 1) {
          const int b = PyObject_IsTrue(v);
          if (b < 0) { ok = false; break; }
          base[t] = static_cast<char>(b);
        } else {
          PyErr_SetString(PyExc_ValueError, "unsupported scalar dtype");
          ok = false;
          break;
        }
      }
      continue;
    }

    if (dst.buf.ndim != 2 || isz != 4) {
      PyErr_SetString(PyExc_ValueError, "mode-0 dst must be 2-D i32/f32");
      ok = false;
      break;
    }
    const Py_ssize_t width = dst.buf.shape[1];
    const Py_ssize_t width_bytes = width * isz;
    // pad value converted once per spec
    float padf = 0.0f;
    int padi = 0;
    if (kind == 'f') {
      const double x = PyFloat_AsDouble(pad_obj);
      if (x == -1.0 && PyErr_Occurred()) { ok = false; break; }
      padf = static_cast<float>(x);
    } else {
      const long x = PyLong_AsLong(pad_obj);
      if (x == -1 && PyErr_Occurred()) { ok = false; break; }
      padi = static_cast<int>(x);
    }
    for (Py_ssize_t i = 0; ok && i < n; ++i) {
      PyObject* d = PySequence_Fast_GET_ITEM(rows, i);
      PyObject* v = PyDict_GetItemWithError(d, key);  // borrowed
      const Py_ssize_t t = idx[i];
      if (!v || t < 0 || t >= n_rows_dst) {
        if (!PyErr_Occurred()) {
          PyErr_SetString(PyExc_KeyError, "apply_rows: bad key/target");
        }
        ok = false;
        break;
      }
      char* out = base + t * width_bytes;
      // pad the whole row first (clears any previous occupant)
      if (kind == 'f') {
        float* of = reinterpret_cast<float*>(out);
        for (Py_ssize_t j = 0; j < width; ++j) of[j] = padf;
      } else {
        int* oi = reinterpret_cast<int*>(out);
        for (Py_ssize_t j = 0; j < width; ++j) oi[j] = padi;
      }
      View rv;
      if (rv.acquire(v, PyBUF_C_CONTIGUOUS)) {
        if (rv.buf.itemsize != isz) {
          PyErr_SetString(PyExc_ValueError, "row buffer itemsize mismatch");
          ok = false;
          break;
        }
        Py_ssize_t bytes = rv.buf.len;
        if (bytes > width_bytes) bytes = width_bytes;
        std::memcpy(out, rv.buf.buf, static_cast<size_t>(bytes));
        continue;
      }
      PyErr_Clear();
      PyObject* rseq = PySequence_Fast(v, "row must be buffer or sequence");
      if (!rseq) { ok = false; break; }
      Py_ssize_t m = PySequence_Fast_GET_SIZE(rseq);
      if (m > width) m = width;
      for (Py_ssize_t j = 0; ok && j < m; ++j) {
        PyObject* e = PySequence_Fast_GET_ITEM(rseq, j);
        if (kind == 'f') {
          const double x = PyFloat_AsDouble(e);
          if (x == -1.0 && PyErr_Occurred()) ok = false;
          else reinterpret_cast<float*>(out)[j] = static_cast<float>(x);
        } else {
          const long x = PyLong_AsLong(e);
          if (x == -1 && PyErr_Occurred()) ok = false;
          else reinterpret_cast<int*>(out)[j] = static_cast<int>(x);
        }
      }
      Py_DECREF(rseq);
    }
  }
  Py_DECREF(specs);
  Py_DECREF(rows);
  if (!ok) return nullptr;
  Py_RETURN_NONE;
}

// ---------------------------------------------------------------------------
// pod_rows_into(pods, ctx, index_i64, specs, limits)
//
// The fused delta-path row builder (PERF.md "Host-encode budget",
// round-5): parses each pod ONCE (parse_pod) and writes its arena row
// straight from the C structs — no rowdata dict, no per-field Python
// lists, no apply_rows re-read. `specs` is the apply_rows spec list
// (dst, key, pad, mode) with one extension: mode-1 float64 columns
// (the creation-timestamp array). `limits` carries the arena dims
// guards {MPL, MA, MPorts, MC, R, flag_aff, flag_tsc}.
//
// Returns (guard_ok, results). results[i] is the pod's encoded port
// list when its row was written natively, or None when the pod needs
// the Python fallback (volumes / nodeAffinity / exotic selector ops —
// caller builds its rowdata dict and apply_rows's just those). guard_ok
// False means some pod exceeded an arena dim: the caller must bail to
// the full encode, which rebuilds every row (partially written arena
// rows are therefore harmless).
// ---------------------------------------------------------------------------
PyObject* pod_rows_into(PyObject*, PyObject* args) {
  PyObject *pods_obj, *ctxd, *index_obj, *specs_obj, *limits;
  if (!PyArg_ParseTuple(args, "OOOOO", &pods_obj, &ctxd, &index_obj,
                        &specs_obj, &limits)) {
    return nullptr;
  }
  Ctx c{};
  if (!load_ctx(ctxd, c)) return nullptr;
  long MPL, MA, MPorts, MC, R, flag_aff, flag_tsc;
  if (!ctx_long(limits, "MPL", &MPL) || !ctx_long(limits, "MA", &MA) ||
      !ctx_long(limits, "MPorts", &MPorts) || !ctx_long(limits, "MC", &MC) ||
      !ctx_long(limits, "R", &R) || !ctx_long(limits, "flag_aff", &flag_aff) ||
      !ctx_long(limits, "flag_tsc", &flag_tsc)) {
    return nullptr;
  }

  View index;
  if (!index.acquire(index_obj, PyBUF_C_CONTIGUOUS)) return nullptr;
  if (index.buf.ndim != 1 ||
      index.buf.itemsize != static_cast<Py_ssize_t>(sizeof(long))) {
    PyErr_SetString(PyExc_ValueError, "index must be 1-D int64");
    return nullptr;
  }
  const long* idx = static_cast<const long*>(index.buf.buf);
  const Py_ssize_t n_idx = index.buf.shape[0];

  // resolve each spec's key to a Parsed field once
  enum Field {
    F_REQVEC, F_LABK, F_LABV, F_PORTS, F_PREFW, F_TSCSKEW,
    F_VOLMODE, F_VOLREQ, F_VOLCLS, F_VOLSIZE,        // empty for native pods
    F_AFF, F_ANTI, F_PREF, F_TSC,
    F_PRIO, F_REQID, F_PREFID, F_SELREQ, F_TOLSET, F_GID, F_IMAGESET,
    F_CANPRE, F_CREATION,
  };
  struct Col {
    int field;
    long mode;
    char kind;
    Py_ssize_t isz, rows, width;
    char* base;
    float padf;
    int padi;
  };
  PyObject* specs = PySequence_Fast(specs_obj, "specs must be a sequence");
  if (!specs) return nullptr;
  const Py_ssize_t n_specs = PySequence_Fast_GET_SIZE(specs);
  std::vector<View> views(static_cast<size_t>(n_specs));
  std::vector<Col> cols;
  cols.reserve(static_cast<size_t>(n_specs));
  bool ok = true;
  for (Py_ssize_t s = 0; ok && s < n_specs; ++s) {
    PyObject* spec = PySequence_Fast_GET_ITEM(specs, s);
    PyObject *dst_obj, *key, *pad_obj, *m;
    if (!PyArg_ParseTuple(spec, "OOOO", &dst_obj, &key, &pad_obj, &m)) {
      ok = false;
      break;
    }
    Col col{};
    col.mode = PyLong_AsLong(m);
    if (col.mode == -1 && PyErr_Occurred()) { ok = false; break; }
    const char* ks = PyUnicode_AsUTF8(key);
    if (!ks) { ok = false; break; }
    if (!strcmp(ks, "reqvec")) col.field = F_REQVEC;
    else if (!strcmp(ks, "lab_k")) col.field = F_LABK;
    else if (!strcmp(ks, "lab_v")) col.field = F_LABV;
    else if (!strcmp(ks, "ports")) col.field = F_PORTS;
    else if (!strcmp(ks, "pref_w")) col.field = F_PREFW;
    else if (!strcmp(ks, "tsc_skew")) col.field = F_TSCSKEW;
    else if (!strcmp(ks, "vol_mode")) col.field = F_VOLMODE;
    else if (!strcmp(ks, "vol_req")) col.field = F_VOLREQ;
    else if (!strcmp(ks, "vol_cls")) col.field = F_VOLCLS;
    else if (!strcmp(ks, "vol_size")) col.field = F_VOLSIZE;
    else if (!strcmp(ks, "aff")) col.field = F_AFF;
    else if (!strcmp(ks, "anti")) col.field = F_ANTI;
    else if (!strcmp(ks, "pref")) col.field = F_PREF;
    else if (!strcmp(ks, "tsc")) col.field = F_TSC;
    else if (!strcmp(ks, "prio")) col.field = F_PRIO;
    else if (!strcmp(ks, "req_id")) col.field = F_REQID;
    else if (!strcmp(ks, "pref_id")) col.field = F_PREFID;
    else if (!strcmp(ks, "sel_req_id")) col.field = F_SELREQ;
    else if (!strcmp(ks, "tolset")) col.field = F_TOLSET;
    else if (!strcmp(ks, "gid")) col.field = F_GID;
    else if (!strcmp(ks, "imageset")) col.field = F_IMAGESET;
    else if (!strcmp(ks, "can_preempt")) col.field = F_CANPRE;
    else if (!strcmp(ks, "creation")) col.field = F_CREATION;
    else {
      PyErr_Format(PyExc_KeyError, "pod_rows_into: unknown key %s", ks);
      ok = false;
      break;
    }
    View& v = views[static_cast<size_t>(s)];
    if (!v.acquire(dst_obj,
                   PyBUF_C_CONTIGUOUS | PyBUF_WRITABLE | PyBUF_FORMAT)) {
      ok = false;
      break;
    }
    col.kind = v.buf.format ? v.buf.format[0] : 'i';
    col.isz = v.buf.itemsize;
    col.base = static_cast<char*>(v.buf.buf);
    col.rows = v.buf.shape[0];
    col.width = col.mode == 0 ? v.buf.shape[1] : 1;
    if (col.mode == 0) {
      if (v.buf.ndim != 2 || col.isz != 4) {
        PyErr_SetString(PyExc_ValueError,
                        "pod_rows_into: mode-0 dst must be 2-D i32/f32");
        ok = false;
        break;
      }
      const double x = PyFloat_AsDouble(pad_obj);
      if (x == -1.0 && PyErr_Occurred()) { ok = false; break; }
      col.padf = static_cast<float>(x);
      col.padi = static_cast<int>(PyLong_AsLong(pad_obj));
      if (col.padi == -1 && PyErr_Occurred()) PyErr_Clear();  // float pad
    } else if (v.buf.ndim != 1) {
      PyErr_SetString(PyExc_ValueError, "pod_rows_into: mode-1 dst not 1-D");
      ok = false;
      break;
    }
    cols.push_back(col);
  }

  PyObject* pods = ok ? PySequence_Fast(pods_obj, "pods must be a sequence")
                      : nullptr;
  if (!pods) {
    Py_DECREF(specs);
    return nullptr;
  }
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(pods);
  PyObject* results = n <= n_idx ? PyList_New(n) : nullptr;
  if (!results) {
    if (!PyErr_Occurred()) {
      PyErr_SetString(PyExc_ValueError, "index shorter than pods");
    }
    Py_DECREF(pods);
    Py_DECREF(specs);
    return nullptr;
  }

  bool guard_ok = true;
  for (Py_ssize_t i = 0; ok && guard_ok && i < n; ++i) {
    PyObject* pod = PySequence_Fast_GET_ITEM(pods, i);
    Parsed P;
    const long st = parse_pod(c, pod, P);
    if (st == -2) { ok = false; break; }
    if (st == -3) {
      PyErr_Clear();
      Py_INCREF(Py_None);
      PyList_SET_ITEM(results, i, Py_None);  // caller's Python fallback
      continue;
    }
    if (static_cast<long>(P.lab_k.size()) > MPL ||
        P.n_aff > MA ||
        static_cast<long>(P.ports.size()) > MPorts ||
        static_cast<long>(P.tsc_skew.size()) > MC ||
        static_cast<long>(P.reqvec.size()) > R ||
        (!flag_aff && P.n_aff > 0) ||
        (!flag_tsc && !P.tsc_skew.empty())) {
      guard_ok = false;  // arena dims too small: full re-encode
      break;
    }
    const Py_ssize_t t = idx[i];
    for (Col& col : cols) {
      if (t < 0 || t >= col.rows) {
        PyErr_SetString(PyExc_IndexError, "pod_rows_into: target row");
        ok = false;
        break;
      }
      if (col.mode == 1) {  // scalar column
        long sv = 0;
        switch (col.field) {
          case F_PRIO: sv = P.prio; break;
          case F_REQID: case F_PREFID: sv = -1; break;
          case F_SELREQ: sv = P.sel_req_id; break;
          case F_TOLSET: sv = P.tolset; break;
          case F_GID: sv = P.gid; break;
          case F_IMAGESET: sv = P.imageset; break;
          case F_CANPRE: sv = P.can_preempt ? 1 : 0; break;
          case F_CREATION: break;
          default:
            PyErr_SetString(PyExc_ValueError,
                            "pod_rows_into: 2-D key on mode-1 spec");
            ok = false;
        }
        if (!ok) break;
        if (col.field == F_CREATION) {
          if (col.isz != 8) {
            PyErr_SetString(PyExc_ValueError, "creation dst must be f64");
            ok = false;
            break;
          }
          reinterpret_cast<double*>(col.base)[t] = P.creation;
        } else if (col.isz == 4) {
          reinterpret_cast<int*>(col.base)[t] = static_cast<int>(sv);
        } else if (col.isz == 1) {
          col.base[t] = static_cast<char>(sv != 0);
        } else {
          PyErr_SetString(PyExc_ValueError, "unsupported scalar dtype");
          ok = false;
          break;
        }
        continue;
      }
      // 2-D row: pad, then copy the vector (guards above ensure fit)
      char* out = col.base + t * col.width * 4;
      const std::vector<long>* vl = nullptr;
      const std::vector<double>* vd = nullptr;
      switch (col.field) {
        case F_REQVEC: vd = &P.reqvec; break;
        case F_PREFW: vd = &P.pref_w; break;
        case F_LABK: vl = &P.lab_k; break;
        case F_LABV: vl = &P.lab_v; break;
        case F_PORTS: vl = &P.ports; break;
        case F_TSCSKEW: vl = &P.tsc_skew; break;
        case F_AFF: vl = &P.aff; break;
        case F_ANTI: vl = &P.anti; break;
        case F_PREF: vl = &P.pref; break;
        case F_TSC: vl = &P.tsc; break;
        case F_VOLMODE: case F_VOLREQ: case F_VOLCLS: case F_VOLSIZE:
          break;  // native pods carry no volumes: pad only
        default:
          PyErr_SetString(PyExc_ValueError,
                          "pod_rows_into: scalar key on mode-0 spec");
          ok = false;
      }
      if (!ok) break;
      if (col.kind == 'f') {
        float* of = reinterpret_cast<float*>(out);
        for (Py_ssize_t j = 0; j < col.width; ++j) of[j] = col.padf;
        if (vd) {
          Py_ssize_t m2 = static_cast<Py_ssize_t>(vd->size());
          if (m2 > col.width) m2 = col.width;
          for (Py_ssize_t j = 0; j < m2; ++j) {
            of[j] = static_cast<float>((*vd)[j]);
          }
        }
      } else {
        int* oi = reinterpret_cast<int*>(out);
        for (Py_ssize_t j = 0; j < col.width; ++j) oi[j] = col.padi;
        if (vl) {
          Py_ssize_t m2 = static_cast<Py_ssize_t>(vl->size());
          if (m2 > col.width) m2 = col.width;
          for (Py_ssize_t j = 0; j < m2; ++j) {
            oi[j] = static_cast<int>((*vl)[j]);
          }
        }
      }
    }
    if (!ok) break;
    PyObject* plist = list_from(P.ports);
    if (!plist) { ok = false; break; }
    PyList_SET_ITEM(results, i, plist);
  }
  Py_DECREF(pods);
  Py_DECREF(specs);
  if (!ok) {
    Py_DECREF(results);
    return nullptr;
  }
  if (!guard_ok) {
    Py_DECREF(results);
    Py_INCREF(Py_None);
    PyObject* ret = PyTuple_Pack(2, Py_False, Py_None);
    Py_DECREF(Py_None);
    return ret;
  }
  // pods past a fallback slot may leave NULL holes if we broke early —
  // cannot happen here (every path either fills or errors), but be safe
  for (Py_ssize_t i = 0; i < n; ++i) {
    if (PyList_GET_ITEM(results, i) == nullptr) {
      Py_INCREF(Py_None);
      PyList_SET_ITEM(results, i, Py_None);
    }
  }
  PyObject* ret = PyTuple_Pack(2, Py_True, results);
  Py_DECREF(results);
  return ret;
}

PyMethodDef methods[] = {
    {"pod_rows_into", pod_rows_into, METH_VARARGS,
     "pod_rows_into(pods, ctx, index_i64, specs, limits): fused parse + "
     "direct arena write; returns (guard_ok, per-pod ports | None)"},
    {"apply_rows", apply_rows, METH_VARARGS,
     "apply_rows(specs, index_i64, rowdicts): batched delta arena write"},
    {"scatter_rows", scatter_rows, METH_VARARGS,
     "scatter_rows(dst2d, rows): dst[i, :len(rows[i])] = rows[i]"},
    {"scatter_rows_at", scatter_rows_at, METH_VARARGS,
     "scatter_rows_at(dst2d, index_i64, rows): dst[index[i], :] = rows[i]"},
    {"fill_scalars", fill_scalars, METH_VARARGS,
     "fill_scalars(dst1d, values): dst[i] = values[i]"},
    {"pod_row", pod_row, METH_VARARGS,
     "pod_row(pod, ctx): native pod_rowdata (None = fall back to Python)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_fastassemble",
    "native snapshot-row assembly (see fastassemble.cc)", -1, methods,
};

}  // namespace

PyMODINIT_FUNC PyInit__fastassemble(void) {
  return PyModule_Create(&module);
}
