"""Native (C++) fast paths for the host runtime.

`_fastassemble` (fastassemble.cc) accelerates snapshot-row assembly — the
steady-state encode bottleneck once per-object rows are cached. Build it
with `make -C k8s_scheduler_tpu/native`; every caller falls back to the
equivalent numpy loops when the extension is absent, and
`HAVE_FASTASSEMBLE` says which path is active. On import we attempt a
one-shot build if a compiler is available and the .so is missing (cheap,
~1s, best-effort)."""

from __future__ import annotations

import os
import subprocess
import sys

HAVE_FASTASSEMBLE = False
scatter_rows = None
scatter_rows_at = None
fill_scalars = None
apply_rows = None
pod_row = None  # native pod_rowdata; None => Python path only
pod_rows_into = None  # fused delta-path writer; None => dict interchange


def _try_import() -> bool:
    global HAVE_FASTASSEMBLE, scatter_rows, scatter_rows_at, fill_scalars
    global pod_row, apply_rows, pod_rows_into
    try:
        from . import _fastassemble  # type: ignore[attr-defined]
    except ImportError:
        return False
    HAVE_FASTASSEMBLE = True
    scatter_rows = _fastassemble.scatter_rows
    scatter_rows_at = _fastassemble.scatter_rows_at
    fill_scalars = _fastassemble.fill_scalars
    pod_row = getattr(_fastassemble, "pod_row", None)
    pod_rows_into = getattr(_fastassemble, "pod_rows_into", None)
    # a stale prebuilt .so may predate newer symbols: fall back to the
    # numpy mirror per symbol, never to None (callers invoke unguarded)
    apply_rows = getattr(_fastassemble, "apply_rows", None) or _py_apply_rows
    return True


def _try_build() -> None:
    here = os.path.dirname(__file__)
    try:
        subprocess.run(
            ["make", "-s", f"PY={sys.executable}"],
            cwd=here,
            timeout=120,
            check=True,
            capture_output=True,
        )
    except Exception:
        pass  # no toolchain / read-only checkout: numpy fallback


def _py_scatter_rows(dst, rows):
    w = dst.shape[1]
    for i, r in enumerate(rows):
        if r is None:
            continue
        n = min(len(r), w)
        dst[i, :n] = r[:n]


def _py_scatter_rows_at(dst, index, rows):
    w = dst.shape[1]
    for i, r in enumerate(rows):
        if r is None:
            continue
        n = min(len(r), w)
        dst[index[i], :n] = r[:n]


def _py_fill_scalars(dst, values):
    n = min(len(values), dst.shape[0])
    dst[:n] = values[:n]


def _py_apply_rows(specs, index, rows):
    """numpy mirror of the native batched delta arena write."""
    for dst, key, pad, mode in specs:
        if mode == 1:
            dst[index] = [d[key] for d in rows]
        else:
            dst[index] = pad
            _py_scatter_rows_at(dst, index, [d[key] for d in rows])


if not _try_import():
    _try_build()
    if not _try_import():
        scatter_rows = _py_scatter_rows
        scatter_rows_at = _py_scatter_rows_at
        fill_scalars = _py_fill_scalars
        apply_rows = _py_apply_rows
