"""Tenant registry: thousands of virtual clusters in one scheduler.

The ROADMAP's north star read at fleet scale is not one giant cluster
but many SMALL ones — per-team, per-model, per-job virtual clusters.
A `Tenant` here is a self-contained virtual cluster: its own nodes,
its own pending/bound pods, and its OWN SnapshotEncoder, so the
incremental-encode machinery works per tenant: the arena packer's
per-cycle `encode_packed` is an O(dirty) delta against that tenant's
arena (the existing-set identity precheck), not a fleet-wide rebuild.
The encoder is serve-thread-owned, same as the single-cluster one —
the admission path never touches it (see `_add_pod_locked`), and the
arena snapshots + encodes under the registry lock (`encode_active`).

Isolation boundary: every per-tenant container lives behind a `_tn_`-
prefixed attribute. schedlint's TENANCY-ISOLATION pass (TN001) forbids
touching `_tn_*` attributes outside this package — the static pin of
the boundary tests/test_tenancy.py checks dynamically (a packed
N-tenant run is bit-equal per tenant to N sequential runs, so no code
path can have read another tenant's slice).

Durability: the registry journals every mutation under `tn.*` ops into
its OWN state.journal.Journal directory. DurableState.restore_into
refuses unknown ops by design, so tenancy neither shares nor corrupts
the scheduler WAL — `restore_registry(directory)` replays the tenancy
directory and reconstructs every virtual cluster (pods, nodes, binds,
quotas, suspensions) after failover. Emission follows the state/
discipline (JE001-003): each public mutator reads the clock exactly
once and emits exactly one record carrying that clock value.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable

from ..models.api import Node, Pod
from ..models.encoding import SnapshotEncoder
from ..state import codec

TENANT_ACTIVE = "active"
TENANT_SUSPENDED = "suspended"

# journal ops this registry emits; see state/journal.py TENANCY_OPS
OP_CREATE = "tn.create"
OP_SUSPEND = "tn.suspend"
OP_RESUME = "tn.resume"
OP_DELETE = "tn.delete"
OP_NODE = "tn.node"
OP_POD = "tn.pod"
OP_UNPOD = "tn.unpod"
OP_BIND = "tn.bind"


class TenantError(ValueError):
    """Base for tenant routing failures (admission maps these to an
    invalid Submit outcome with a tenant-scoped reason)."""


class UnknownTenant(TenantError):
    pass


class TenantSuspended(TenantError):
    pass


class Tenant:
    """One virtual cluster. Mutated only through TenantRegistry (which
    holds the lock and the journal); read freely via the accessors."""

    def __init__(self, tenant_id: str, *, quota: int = 0,
                 weight: float = 1.0) -> None:
        self.id = str(tenant_id)
        # admission ceiling on accepted-unbound pods; 0 = unlimited
        self.quota = int(quota)
        # weighted-fair share of the global admission depth bound
        self.weight = float(weight)
        # active/suspended; named `lifecycle` (not `state`) on purpose —
        # the name `state` collides with the device keepers' `state`
        # methods in schedlint's name-based callgraph, which would smear
        # the HTTP role across the dispatch path (the admission.py
        # `_durable` precedent)
        self.lifecycle = TENANT_ACTIVE
        # the virtual cluster proper — `_tn_` prefix IS the isolation
        # boundary (TN001): nothing outside tenancy/ may touch these
        self._tn_nodes: list[Node] = []
        self._tn_node_names: set[str] = set()
        self._tn_pending: dict[str, Pod] = {}  # uid -> pod, arrival order
        self._tn_bound: dict[str, tuple[Pod, str]] = {}
        self._tn_existing: tuple[tuple[Pod, str], ...] = ()
        self._tn_encoder = SnapshotEncoder()
        self.submitted_total = 0
        self.bound_total = 0
        # consecutive arena cycles with pending pods and zero binds
        # while other tenants bound — the starved-tenant signal
        self.starve_streak = 0

    # ---- read side ------------------------------------------------------

    def depth(self) -> int:
        return len(self._tn_pending)

    def node_count(self) -> int:
        return len(self._tn_nodes)

    def bound_count(self) -> int:
        return len(self._tn_bound)

    def pending_pods(self) -> list[Pod]:
        return list(self._tn_pending.values())

    def has_pod(self, uid: str) -> bool:
        return uid in self._tn_pending or uid in self._tn_bound

    def bound_node(self, uid: str) -> str | None:
        entry = self._tn_bound.get(uid)
        return entry[1] if entry else None

    def encode_frame(self):
        """Encode this tenant's snapshot into ITS arena buffers (delta
        when only the pending set moved). Returns models.encoding
        EncodedFrame. Serve-thread only, like the encoder itself."""
        return self._tn_encoder.encode_packed(
            self._tn_nodes,
            list(self._tn_pending.values()),
            self._tn_existing,
        )

    def status(self) -> dict:
        return {
            "id": self.id,
            "state": self.lifecycle,
            "quota": self.quota,
            "weight": self.weight,
            "nodes": len(self._tn_nodes),
            "pending": len(self._tn_pending),
            "bound": len(self._tn_bound),
            "submitted_total": self.submitted_total,
            "bound_total": self.bound_total,
            "starve_streak": self.starve_streak,
        }


class TenantRegistry:
    """Create/suspend/delete virtual clusters; route pods and nodes
    into them; fold binds back. Thread-safe; journaled (see module
    docstring). The arena packer (tenancy/arena.py) drives the
    schedule side; service/admission.py consults quotas and depths."""

    def __init__(self, *, metrics=None,
                 now: Callable[[], float] = time.monotonic) -> None:
        self._lock = threading.RLock()
        self._tenants: dict[str, Tenant] = {}
        self._journal: Callable[[str, float, dict], None] | None = None
        self._now = now
        self._metrics = metrics

    def set_journal(
        self, journal: Callable[[str, float, dict], None] | None
    ) -> None:
        with self._lock:
            self._journal = journal

    def _emit(self, op: str, t: float, data: dict) -> None:
        if self._journal is not None:
            self._journal(op, t, data)

    def _event(self, event: str) -> None:
        m = self._metrics
        if m is not None:
            m.tenancy_events.labels(event=event).inc()

    # ---- lifecycle mutators ---------------------------------------------

    def create(self, tenant_id: str, *, quota: int = 0,
               weight: float = 1.0) -> Tenant:
        with self._lock:
            now = self._now()
            t = self._create_locked(tenant_id, quota, weight)
            self._event("created")
            self._emit(OP_CREATE, now, {
                "id": t.id, "quota": t.quota, "weight": t.weight,
            })
            return t

    def suspend(self, tenant_id: str) -> None:
        with self._lock:
            now = self._now()
            self._require_locked(tenant_id).lifecycle = TENANT_SUSPENDED
            self._event("suspended")
            self._emit(OP_SUSPEND, now, {"id": tenant_id})

    def resume(self, tenant_id: str) -> None:
        with self._lock:
            now = self._now()
            self._require_locked(tenant_id).lifecycle = TENANT_ACTIVE
            self._event("resumed")
            self._emit(OP_RESUME, now, {"id": tenant_id})

    def delete(self, tenant_id: str) -> None:
        with self._lock:
            now = self._now()
            self._require_locked(tenant_id)
            del self._tenants[tenant_id]
            self._event("deleted")
            self._emit(OP_DELETE, now, {"id": tenant_id})

    # ---- membership mutators --------------------------------------------

    def add_node(self, tenant_id: str, node: Node) -> None:
        with self._lock:
            now = self._now()
            self._add_node_locked(tenant_id, node)
            self._emit(OP_NODE, now, {
                "id": tenant_id, "node": codec.node_to_state(node),
            })

    def add_pod(self, tenant_id: str, pod: Pod) -> None:
        """Route one pod into its tenant's pending set (raises
        UnknownTenant / TenantSuspended — admission turns these into
        invalid outcomes). The next encode_frame picks it up as a
        delta row (existing-set precheck), not a rebuild."""
        with self._lock:
            now = self._now()
            self._add_pod_locked(tenant_id, pod)
            self._emit(OP_POD, now, {
                "id": tenant_id, "pod": codec.pod_to_state(pod),
            })

    def remove_pod(self, tenant_id: str, uid: str) -> None:
        with self._lock:
            now = self._now()
            self._remove_pod_locked(tenant_id, uid)
            self._emit(OP_UNPOD, now, {"id": tenant_id, "uid": uid})

    def bind(self, tenant_id: str, uid: str, node_name: str) -> None:
        """Fold one arena decision: pending -> bound on `node_name`."""
        with self._lock:
            now = self._now()
            self._bind_locked(tenant_id, uid, node_name)
            self._emit(OP_BIND, now, {
                "id": tenant_id, "uid": uid, "node": node_name,
            })

    def route(self, pod: Pod) -> None:
        """Tenant identity rides the pod's namespace (ObjectMeta.uid is
        namespace-qualified, so same-named pods in different tenants
        never collide)."""
        self.add_pod(pod.namespace, pod)

    # ---- non-emitting internals (replay shares these) -------------------

    def _create_locked(self, tenant_id: str, quota, weight) -> Tenant:
        if tenant_id in self._tenants:
            raise TenantError(f"tenant {tenant_id!r} already exists")
        t = Tenant(str(tenant_id), quota=int(quota), weight=float(weight))
        self._tenants[t.id] = t
        return t

    def _require_locked(self, tenant_id: str) -> Tenant:
        t = self._tenants.get(tenant_id)
        if t is None:
            raise UnknownTenant(f"unknown tenant {tenant_id!r}")
        return t

    def _add_node_locked(self, tenant_id: str, node: Node) -> None:
        t = self._require_locked(tenant_id)
        if node.name in t._tn_node_names:
            raise TenantError(
                f"node {node.name!r} already in tenant {tenant_id!r}"
            )
        t._tn_nodes.append(node)
        t._tn_node_names.add(node.name)

    def _add_pod_locked(self, tenant_id: str, pod: Pod) -> None:
        t = self._require_locked(tenant_id)
        if t.lifecycle != TENANT_ACTIVE:
            raise TenantSuspended(f"tenant {tenant_id!r} is suspended")
        if pod.uid in t._tn_pending or pod.uid in t._tn_bound:
            raise TenantError(
                f"pod {pod.uid!r} already in tenant {tenant_id!r}"
            )
        t._tn_pending[pod.uid] = pod
        t.submitted_total += 1
        # deliberately NO encoder touch here: this runs on the admission
        # (httpserver) thread, and the per-tenant encoder is serve-
        # thread-owned exactly like the single-cluster one (scheduler's
        # _ingest_group comment). The PR 16 reuse is the delta path in
        # encode_frame — the existing-set identity precheck makes the
        # cycle-time encode O(new pods), not a fleet rebuild.

    def _remove_pod_locked(self, tenant_id: str, uid: str) -> None:
        t = self._require_locked(tenant_id)
        if t._tn_pending.pop(uid, None) is None:
            if t._tn_bound.pop(uid, None) is None:
                raise TenantError(
                    f"pod {uid!r} not in tenant {tenant_id!r}"
                )
            t._tn_existing = tuple(t._tn_bound.values())

    def _bind_locked(self, tenant_id: str, uid: str,
                     node_name: str) -> None:
        t = self._require_locked(tenant_id)
        pod = t._tn_pending.pop(uid, None)
        if pod is None:
            raise TenantError(
                f"pod {uid!r} not pending in tenant {tenant_id!r}"
            )
        if node_name not in t._tn_node_names:
            raise TenantError(
                f"node {node_name!r} not in tenant {tenant_id!r}"
            )
        t._tn_bound[uid] = (pod, node_name)
        # a NEW tuple only when the bound set actually changed: the
        # per-tenant delta encoder keys its existing-set precheck on
        # object identity first, element ids second
        t._tn_existing = tuple(t._tn_bound.values())
        t.bound_total += 1

    # ---- replay ---------------------------------------------------------

    def apply(self, op: str, t: float, data: dict) -> None:
        """Apply one journal record WITHOUT re-emitting (restore path).
        Unknown `tn.*` ops refuse loudly: the tenancy journal directory
        is owned by this class alone, so an unknown op is corruption or
        version skew, and silently skipping it would resurrect as a
        divergent virtual cluster after failover."""
        if op == OP_CREATE:
            self._create_locked(
                data["id"], data.get("quota", 0), data.get("weight", 1.0)
            )
        elif op == OP_SUSPEND:
            self._require_locked(data["id"]).lifecycle = TENANT_SUSPENDED
        elif op == OP_RESUME:
            self._require_locked(data["id"]).lifecycle = TENANT_ACTIVE
        elif op == OP_DELETE:
            self._require_locked(data["id"])
            del self._tenants[data["id"]]
        elif op == OP_NODE:
            self._add_node_locked(
                data["id"], codec.node_from_state(data["node"])
            )
        elif op == OP_POD:
            tid = data["id"]
            # replay must land pods into suspended tenants too (the
            # suspension may postdate the pod in the op sequence)
            t_obj = self._require_locked(tid)
            st, t_obj.lifecycle = t_obj.lifecycle, TENANT_ACTIVE
            try:
                self._add_pod_locked(tid, codec.pod_from_state(data["pod"]))
            finally:
                t_obj.lifecycle = st
        elif op == OP_UNPOD:
            self._remove_pod_locked(data["id"], data["uid"])
        elif op == OP_BIND:
            self._bind_locked(data["id"], data["uid"], data["node"])
        else:
            raise ValueError(f"unknown tenancy journal op {op!r}")

    # ---- serve-thread encode --------------------------------------------

    def encode_active(self) -> list[tuple]:
        """One consistent fleet snapshot for the arena cycle: under the
        lock, encode every active tenant with pending demand and capture
        the EXACT pending order and node table each frame was built
        from. The fold maps decision slots back through these captured
        lists — never through live `_tn_pending`/`_tn_nodes`, which the
        admission thread keeps mutating once the lock drops. Serve
        thread only (the encoders are serve-thread-owned); admission
        blocks for the encode, which the per-tenant delta path keeps to
        O(new pods). Returns [(tenant, frame, pending, nodes), ...]."""
        with self._lock:
            out = []
            for t in self._tenants.values():
                if t.lifecycle != TENANT_ACTIVE or not t._tn_pending:
                    continue
                out.append((
                    t,
                    t.encode_frame(),
                    list(t._tn_pending.values()),
                    tuple(t._tn_nodes),
                ))
            return out

    # ---- read side ------------------------------------------------------

    def get(self, tenant_id: str) -> Tenant | None:
        with self._lock:
            return self._tenants.get(tenant_id)

    def require(self, tenant_id: str) -> Tenant:
        with self._lock:
            return self._require_locked(tenant_id)

    def ids(self) -> list[str]:
        with self._lock:
            return list(self._tenants)

    def tenants(self) -> list[Tenant]:
        with self._lock:
            return list(self._tenants.values())

    def active(self) -> list[Tenant]:
        with self._lock:
            return [
                t for t in self._tenants.values()
                if t.lifecycle == TENANT_ACTIVE
            ]

    def depth(self, tenant_id: str) -> int:
        with self._lock:
            t = self._tenants.get(tenant_id)
            return t.depth() if t else 0

    def has_pod(self, uid: str) -> bool:
        with self._lock:
            return any(t.has_pod(uid) for t in self._tenants.values())

    def total_weight(self) -> float:
        with self._lock:
            return sum(
                t.weight for t in self._tenants.values()
                if t.lifecycle == TENANT_ACTIVE
            ) or 1.0

    def status(self) -> dict:
        with self._lock:
            return {
                "tenants": len(self._tenants),
                "active": sum(
                    1 for t in self._tenants.values()
                    if t.lifecycle == TENANT_ACTIVE
                ),
                "pending": sum(
                    t.depth() for t in self._tenants.values()
                ),
                "bound": sum(
                    t.bound_count() for t in self._tenants.values()
                ),
            }


def restore_registry(
    directory: str, *, metrics=None,
    now: Callable[[], float] = time.monotonic,
) -> TenantRegistry:
    """Failover: rebuild every virtual cluster from the tenancy journal
    directory (see state/journal.py replay_dir for torn-tail rules)."""
    from ..state import journal as _journal

    reg = TenantRegistry(metrics=metrics, now=now)
    for op, t, data in _journal.replay_dir(directory):
        reg.apply(op, t, data)
    return reg


def iter_pods(tenants: Iterable[Tenant]):
    """(tenant_id, pod) across tenants' pending sets, arrival order."""
    for t in tenants:
        for pod in t.pending_pods():
            yield t.id, pod
