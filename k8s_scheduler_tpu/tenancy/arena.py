"""The arena packer: one compiled program schedules every tenant.

Each tenant's snapshot packs (models/packing.py) into two flat buffers
whose layout is fully determined by its PackSpec key — and tenant
workloads quantize into a SMALL set of keys, because the encoder
already pads every dimension to pow2/bucketed sizes. The arena stacks
same-key tenants' buffers along a leading batch axis (u32 [T, W] /
u8 [T, B]) and dispatches core.cycle.build_arena_cycle_fn ONCE per
(spec bucket, T bucket): one compile-cache entry, one pad regime, all
tenants scheduled per dispatch. T is padded to pow2 with zero rows —
a zero buffer unpacks to an all-invalid snapshot that decides nothing
— so tenant churn moves between a handful of executables instead of
recompiling.

The per-row op chain is exactly the single-tenant packed program's
(`_make_cycle_body` shared), which is what makes the isolation
contract testable: a packed N-tenant run is BIT-EQUAL per tenant to N
sequential single-tenant runs (tests/test_tenancy.py, including under
the fuzz multi-tenant grammar). `MultiTenantArena.inject` exists for
those tests: it plants a deliberate cross-tenant leak (rolling result
rows within a bucket) so the property suite and the fuzz shrinker can
prove they would catch one.
"""

from __future__ import annotations

import time

import numpy as np

from ..core.cycle import build_arena_cycle_fn, build_packed_cycle_fn
from .registry import Tenant, TenantError, TenantRegistry


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= n (min 1): the tenant-count pad policy.
    Buckets keep the executable set logarithmic in fleet size; zero
    rows make the pad inert."""
    b = 1
    while b < n:
        b <<= 1
    return b


class ArenaPacker:
    """Builds, caches, and dispatches arena programs. One entry per
    (PackSpec.key(), padded tenant count): `builds` counts entries
    created (= warmup compiles), `dispatches` counts launches — the
    bench's zero-compiles-after-warmup gate is `builds` staying flat
    while `dispatches` grows."""

    def __init__(self, *, framework=None, commit_mode: str = "rounds",
                 gang_scheduling: bool = True, max_rounds: int = 64) -> None:
        self._kw = dict(
            framework=framework,
            commit_mode=commit_mode,
            gang_scheduling=gang_scheduling,
            max_rounds=max_rounds,
        )
        self._fns: dict = {}  # (spec_key, t_pad) -> arena fn
        self.builds = 0
        self.dispatches = 0
        self.tenants_packed = 0

    def fn_for(self, spec, t_pad: int):
        key = (spec.key(), t_pad)
        fn = self._fns.get(key)
        if fn is None:
            fn = build_arena_cycle_fn(spec, **self._kw)
            self._fns[key] = fn
            self.builds += 1
        return fn

    def dispatch(self, spec, bufs: "list[tuple]"):
        """Stack [(wbuf, bbuf), ...] (all layout-compatible with
        `spec`), pad T to its pow2 bucket, run the arena program.
        Returns the batched CycleResult; rows >= len(bufs) are pad."""
        t_real = len(bufs)
        t_pad = pow2_bucket(t_real)
        ws = np.zeros((t_pad, len(bufs[0][0])), np.uint32)
        bs = np.zeros((t_pad, len(bufs[0][1])), np.uint8)
        for i, (w, b) in enumerate(bufs):
            ws[i] = w
            bs[i] = b
        fn = self.fn_for(spec, t_pad)
        self.dispatches += 1
        self.tenants_packed += t_real
        return fn(ws, bs)


class MultiTenantArena:
    """The multi-tenant serve loop: encode every active tenant (delta
    per tenant), group by spec key, one arena dispatch per (bucket,
    T-pad), fold each row's decisions back into its tenant. In
    `sequential=True` mode the same cycle runs one single-tenant
    packed dispatch per tenant instead — the reference stream the
    bit-equality property (and the headline bench) compares against."""

    def __init__(self, registry: TenantRegistry, *, framework=None,
                 commit_mode: str = "rounds", gang_scheduling: bool = True,
                 max_rounds: int = 64, sequential: bool = False,
                 observer=None, metrics=None, starve_after: int = 8) -> None:
        self.registry = registry
        self.sequential = sequential
        self.packer = ArenaPacker(
            framework=framework, commit_mode=commit_mode,
            gang_scheduling=gang_scheduling, max_rounds=max_rounds,
        )
        self._seq_kw = dict(
            framework=framework, commit_mode=commit_mode,
            gang_scheduling=gang_scheduling, max_rounds=max_rounds,
        )
        self._seq_fns: dict = {}  # spec_key -> packed single-tenant fn
        self.observer = observer
        self.metrics = metrics
        self.starve_after = int(starve_after)
        self.on_bind = None  # callable(uid): admission bind-latency hook
        self.cycle_seq = 0
        # test-only fault injection ("row_skew"): roll decision rows
        # within a bucket — a synthetic cross-tenant leak the property
        # suite and the fuzz shrinker must catch
        self.inject: str | None = None
        self.last_decisions: list[tuple] = []

    # ---- dispatch -------------------------------------------------------

    def _seq_fn(self, spec):
        key = spec.key()
        fn = self._seq_fns.get(key)
        if fn is None:
            fn = build_packed_cycle_fn(spec, **self._seq_kw)
            self._seq_fns[key] = fn
        return fn

    def run_cycle(self) -> dict:
        """One fleet-wide scheduling cycle. Returns per-cycle stats;
        the full decision stream (tenant_id, pod_uid, node_name|None)
        is kept on `last_decisions` in (bucket, tenant, slot) order."""
        self.cycle_seq += 1
        # one consistent snapshot+encode under the registry lock; the
        # fold below maps decisions through the CAPTURED pending order
        # and node table, immune to concurrent admission traffic
        work = self.registry.encode_active()

        decisions: list[tuple] = []
        bound_by: dict[str, int] = {}
        dispatches = 0
        # device window only (launch + decision fetch, np.asarray is
        # the sync point): what the arena packing actually amortizes,
        # vs the per-tenant host encode/fold both modes pay alike
        device_s = 0.0
        if self.sequential:
            for t, frame, pending, nodes in work:
                t0 = time.perf_counter()
                res = self._seq_fn(frame.spec)(frame.wbuf, frame.bbuf)
                asg = np.asarray(res.assignment)
                device_s += time.perf_counter() - t0
                dispatches += 1
                self._fold_row(
                    t, pending, nodes, asg, decisions, bound_by,
                )
        else:
            groups: dict = {}  # spec_key -> (canonical spec, items)
            for item in work:
                k = item[1].spec.key()
                if k not in groups:
                    groups[k] = (item[1].spec, [])
                groups[k][1].append(item)
            for spec, items in groups.values():
                t0 = time.perf_counter()
                res = self.packer.dispatch(
                    spec, [(f.wbuf, f.bbuf) for _, f, _, _ in items]
                )
                asg = np.asarray(res.assignment)
                device_s += time.perf_counter() - t0
                dispatches += 1
                if self.inject == "row_skew" and len(items) > 1:
                    asg = np.roll(asg[: len(items)], 1, axis=0)
                for i, (t, _frame, pending, nodes) in enumerate(items):
                    self._fold_row(
                        t, pending, nodes, asg[i], decisions, bound_by
                    )
            m = self.metrics
            if m is not None:
                for _spec, items in groups.values():
                    m.arena_dispatches.inc()
                    m.arena_tenants.observe(len(items))

        self._note_starvation(bound_by)
        self.last_decisions = decisions
        bound = sum(bound_by.values())
        return {
            "cycle": self.cycle_seq,
            "tenants": len(work),
            "dispatches": dispatches,
            "bound": bound,
            "unschedulable": len(decisions) - bound,
            "builds": self.packer.builds,
            "device_s": device_s,
        }

    def _fold_row(self, tenant: Tenant, pending, nodes, asg_row,
                  decisions: list, bound_by: dict) -> None:
        """Fold one tenant's decision row: winners bind into the
        tenant's virtual cluster (same nodes[assignment] mapping as the
        scheduler's apply phase), losers stay pending for the next
        cycle. `pending`/`nodes` are the encode-time captures from
        encode_active — the decision slots index THOSE, not whatever
        the live tenant holds by fold time. Slots >= the tenant's real
        pending count are pad."""
        for j, pod in enumerate(pending):
            a = int(asg_row[j])
            if 0 <= a < len(nodes):
                node_name = nodes[a].name
                try:
                    self.registry.bind(tenant.id, pod.uid, node_name)
                except TenantError:
                    # the pod or tenant left between encode and fold
                    # (delete/suspend raced the cycle): drop the
                    # decision, nothing to roll back
                    decisions.append((tenant.id, pod.uid, None))
                    continue
                bound_by[tenant.id] = bound_by.get(tenant.id, 0) + 1
                if self.on_bind is not None:
                    self.on_bind(pod.uid)
                decisions.append((tenant.id, pod.uid, node_name))
            else:
                decisions.append((tenant.id, pod.uid, None))

    def _note_starvation(self, bound_by: dict) -> None:
        """A tenant with pending demand that binds nothing for
        `starve_after` consecutive cycles WHILE other tenants bind is
        starved — cross-tenant unfairness the per-tenant bit-equality
        property cannot see (each tenant's stream is individually
        correct). Raised once per streak through the observer so
        /debug/anomalies is the one place to look."""
        others_bound = bool(bound_by)
        for t in self.registry.active():
            if t.depth() == 0 or bound_by.get(t.id):
                t.starve_streak = 0
                continue
            if not others_bound:
                continue  # fleet-wide stall is not per-tenant starvation
            t.starve_streak += 1
            if t.starve_streak == self.starve_after:
                if self.observer is not None:
                    self.observer.raise_anomaly(
                        "tenant_starved",
                        seq=self.cycle_seq,
                        profile=t.id,
                        phase="arena",
                        tenant=t.id,
                        pending=t.depth(),
                        streak=t.starve_streak,
                    )
                m = self.metrics
                if m is not None:
                    m.tenancy_events.labels(event="starved").inc()
