"""Multi-tenant arena: thousands of virtual clusters on one compiled
program. See registry.py (tenant lifecycle + durability), arena.py
(the batched dispatch), host.py (the front-door adapter)."""

from .arena import ArenaPacker, MultiTenantArena, pow2_bucket
from .host import TenantFrontHost
from .registry import (
    TENANT_ACTIVE,
    TENANT_SUSPENDED,
    Tenant,
    TenantError,
    TenantRegistry,
    TenantSuspended,
    UnknownTenant,
    restore_registry,
)

__all__ = [
    "ArenaPacker",
    "MultiTenantArena",
    "TenantFrontHost",
    "TENANT_ACTIVE",
    "TENANT_SUSPENDED",
    "Tenant",
    "TenantError",
    "TenantRegistry",
    "TenantSuspended",
    "UnknownTenant",
    "pow2_bucket",
    "restore_registry",
]
