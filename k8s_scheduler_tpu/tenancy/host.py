"""TenantFrontHost: the arena behind the existing front door.

service/admission.py's AdmissionController (and FrontDoor around it)
talks to a `scheduler` through a narrow duck-typed surface: config,
metrics, a queue with a depth, a cache that answers has_pod, an
informer-path `on_pod_add`, a clock. This adapter presents that
surface over a TenantRegistry + MultiTenantArena, so the PR 13 Submit
path — whole-request atomicity, WAL-before-ack, shed semantics,
/debug/explain history — fronts thousands of virtual clusters without
a fork of the admission layer: a Submit carries its tenant in the pod
namespace, admission consults that tenant's quota and weighted-fair
share, and accepted pods route into their tenant's arena slot.
"""

from __future__ import annotations

import time
from types import SimpleNamespace

from ..config.types import SchedulerConfiguration
from ..metrics.metrics import SchedulerMetrics
from .arena import MultiTenantArena
from .registry import TenantRegistry


class _ArenaQueueView:
    """Queue-shaped read view over every tenant's pending set (the
    admission depth bound counts fleet-wide pending, same as the
    single-cluster queue)."""

    def __init__(self, registry: TenantRegistry) -> None:
        self._registry = registry

    def __len__(self) -> int:
        return sum(t.depth() for t in self._registry.tenants())

    def pending_counts(self) -> dict:
        return {"active": len(self)}


class _ArenaCacheView:
    """Cache-shaped dup check: a uid any tenant knows (pending OR
    bound) is a duplicate — same lost-ack retry semantics as the
    single-cluster cache.has_pod."""

    def __init__(self, registry: TenantRegistry) -> None:
        self._registry = registry

    def has_pod(self, uid: str) -> bool:
        return self._registry.has_pod(uid)


class _NoLadder:
    """The arena serve loop has no degradation ladder yet; rung 0 =
    the admission predicate's healthy reading."""

    rung = 0


class TenantFrontHost:
    """Duck-typed scheduler surface for AdmissionController/FrontDoor,
    backed by the tenant registry and the arena packer."""

    def __init__(
        self,
        registry: TenantRegistry,
        *,
        config: SchedulerConfiguration | None = None,
        metrics: SchedulerMetrics | None = None,
        observer=None,
        arena: MultiTenantArena | None = None,
        state=None,
    ) -> None:
        self.registry = registry
        self.config = config or SchedulerConfiguration()
        self.metrics = metrics or SchedulerMetrics()
        self.observer = observer
        self.arena = arena or MultiTenantArena(
            registry, observer=observer, metrics=self.metrics
        )
        self.queue = _ArenaQueueView(registry)
        self.cache = _ArenaCacheView(registry)
        self._mc_groups: dict = {}  # no multi-cycle buffers in arena mode
        self.ladder = _NoLadder()
        self.state = state  # DurableState-shaped ack-barrier provider
        self.admission = None  # AdmissionController installs itself

    # ---- informer-path surface ------------------------------------------

    def on_pod_add(self, pod) -> None:
        self.registry.route(pod)

    def on_node_add(self, node) -> None:
        # nodes are namespaced here the same way pods are: the tenant
        # rides ObjectMeta.namespace (virtual clusters own their nodes)
        self.registry.add_node(node.metadata.namespace, node)

    def on_node_update(self, node) -> None:
        raise NotImplementedError(
            "arena node update not supported yet (delete + add)"
        )

    def on_node_delete(self, name: str) -> None:
        raise NotImplementedError(
            "arena node delete not supported yet"
        )

    def _now(self) -> float:
        return time.monotonic()

    # ---- serve loop ------------------------------------------------------

    def schedule_cycle(self):
        """One fleet cycle for FrontDoor: returns a stats object with
        the `attempted` field the idle/drain logic reads."""
        adm = self.admission
        if adm is not None and self.arena.on_bind is None:
            # close the submit->bind latency window on arena folds
            self.arena.on_bind = adm.note_bind
        stats = self.arena.run_cycle()
        return SimpleNamespace(
            attempted=stats["bound"] + stats["unschedulable"],
            **stats,
        )
