"""Durable scheduler state: write-ahead journal + snapshots + restore.

SURVEY.md §5 item 3 assumes a standby "rebuilds all state from the
agent's re-list"; in this reproduction there is no agent to re-list
from, so a takeover used to silently drop the `SchedulingQueue`'s
backoff deadlines and attempt counts and the `SchedulerCache`'s
assumed-but-unconfirmed pods. This package is the crash-consistent
state layer that closes that gap:

- `journal.py` — checksummed, segment-rotated write-ahead journal of
  logical queue/cache mutations, drained by a writer thread with group
  fsync (appends never touch the bind path's latency budget);
- `codec.py` — fast hand-rolled Pod/Node <-> plain-dict converters
  (the journal/snapshot wire format) plus the canonical state digest;
- `snapshot.py` — atomic whole-state snapshots that compact the
  journal (write-temp + fsync + rename);
- `manager.py` — `DurableState`: wires emitters into a live
  queue/cache pair, restores snapshot+tail on attach, snapshots on an
  interval, and seals the journal on clean shutdown.

Replay is exact: each journal record carries the emitting clock value
and restore re-executes the logical operation under a replay clock, so
backoff expiries, attempt counts, and assumed-pod TTL deadlines come
back bit-identical (differential tests in tests/test_state_failover.py).
Timestamps are CLOCK_MONOTONIC of the host — valid for same-host
failover (the FileLease deployment shape); snapshots carry a wall-clock
anchor for observability.
"""

from .journal import (
    FORMAT_VERSION,
    Journal,
    StateCorruption,
    StateError,
    StateVersionError,
    replay_dir,
)
from .codec import state_digest
from .manager import DurableState

__all__ = [
    "FORMAT_VERSION",
    "Journal",
    "DurableState",
    "StateCorruption",
    "StateError",
    "StateVersionError",
    "replay_dir",
    "state_digest",
]
