"""Pod/Node <-> plain-dict converters for the journal/snapshot wire format.

Hand-rolled instead of `dataclasses.asdict` because the journal emits on
the scheduling hot path: asdict deep-copies recursively through every
nested dataclass (~10x slower than building the dict directly), and the
bind-path overhead budget for journaling is <5% of cycle p50
(ISSUE acceptance). Omit-empty convention: fields at their dataclass
default are skipped, and `*_from_state` fills the same defaults back in,
so records stay small and the round trip is exact.

Also home to `state_digest`: the canonical SHA-256 over a queue+cache
state dump, used by the differential failover tests and
scripts/soak_failover.py to prove a restored standby is bit-identical
to the pre-crash active.
"""

from __future__ import annotations

import hashlib
import json

from ..models.api import (
    Affinity,
    Container,
    ContainerImage,
    ContainerPort,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodSpec,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)

# ---------------------------------------------------------------------------
# selector / affinity helpers
# ---------------------------------------------------------------------------


def _req_to(r: NodeSelectorRequirement) -> dict:
    d = {"k": r.key, "o": r.operator}
    if r.values:
        d["v"] = list(r.values)
    return d


def _req_from(d: dict) -> NodeSelectorRequirement:
    return NodeSelectorRequirement(
        key=d["k"], operator=d["o"], values=tuple(d.get("v", ()))
    )


def _term_to(t: NodeSelectorTerm) -> dict:
    d = {}
    if t.match_expressions:
        d["e"] = [_req_to(r) for r in t.match_expressions]
    if t.match_fields:
        d["f"] = [_req_to(r) for r in t.match_fields]
    return d


def _term_from(d: dict) -> NodeSelectorTerm:
    return NodeSelectorTerm(
        match_expressions=tuple(_req_from(r) for r in d.get("e", ())),
        match_fields=tuple(_req_from(r) for r in d.get("f", ())),
    )


def _lsel_to(s: LabelSelector) -> dict:
    d = {}
    if s.match_labels:
        d["l"] = dict(s.match_labels)
    if s.match_expressions:
        d["e"] = [_req_to(r) for r in s.match_expressions]
    return d


def _lsel_from(d: dict) -> LabelSelector:
    return LabelSelector(
        match_labels=dict(d.get("l", {})),
        match_expressions=tuple(_req_from(r) for r in d.get("e", ())),
    )


def _pat_to(t: PodAffinityTerm) -> dict:
    d = {"s": _lsel_to(t.label_selector), "tk": t.topology_key}
    if t.namespaces:
        d["ns"] = list(t.namespaces)
    return d


def _pat_from(d: dict) -> PodAffinityTerm:
    return PodAffinityTerm(
        label_selector=_lsel_from(d.get("s", {})),
        topology_key=d.get("tk", ""),
        namespaces=tuple(d.get("ns", ())),
    )


def _aff_to(a: Affinity | None) -> dict | None:
    if a is None:
        return None
    out: dict = {}
    na = a.node_affinity
    if na is not None:
        out["n"] = {
            "r": [_term_to(t) for t in na.required],
            "p": [
                {"w": p.weight, "t": _term_to(p.preference)}
                for p in na.preferred
            ],
        }
    for key, pa in (("a", a.pod_affinity), ("x", a.pod_anti_affinity)):
        if pa is not None:
            out[key] = {
                "r": [_pat_to(t) for t in pa.required],
                "p": [
                    {"w": w.weight, "t": _pat_to(w.term)}
                    for w in pa.preferred
                ],
            }
    return out


def _aff_from(d: dict | None) -> Affinity | None:
    if not d:
        return None
    na = None
    if "n" in d:
        nd = d["n"]
        na = NodeAffinity(
            required=tuple(_term_from(t) for t in nd.get("r", ())),
            preferred=tuple(
                PreferredSchedulingTerm(p["w"], _term_from(p["t"]))
                for p in nd.get("p", ())
            ),
        )
    pa = pan = None
    for key, cls in (("a", PodAffinity), ("x", PodAntiAffinity)):
        if key in d:
            pd = d[key]
            obj = cls(
                required=tuple(_pat_from(t) for t in pd.get("r", ())),
                preferred=tuple(
                    WeightedPodAffinityTerm(w["w"], _pat_from(w["t"]))
                    for w in pd.get("p", ())
                ),
            )
            if key == "a":
                pa = obj
            else:
                pan = obj
    return Affinity(node_affinity=na, pod_affinity=pa, pod_anti_affinity=pan)


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------


def pod_to_state(p: Pod) -> dict:
    m = p.metadata
    s = p.spec
    meta: dict = {"n": m.name}
    if m.namespace != "default":
        meta["ns"] = m.namespace
    meta["u"] = m.uid
    if m.labels:
        meta["l"] = dict(m.labels)
    if m.annotations:
        meta["a"] = dict(m.annotations)
    if m.creation_timestamp:
        meta["ct"] = m.creation_timestamp
    spec: dict = {}
    if s.containers:
        spec["c"] = [
            {
                "n": c.name,
                **({"i": c.image} if c.image else {}),
                **({"r": dict(c.requests)} if c.requests else {}),
                **(
                    {
                        "p": [
                            {
                                "cp": pt.container_port,
                                "hp": pt.host_port,
                                "pr": pt.protocol,
                                "ip": pt.host_ip,
                            }
                            for pt in c.ports
                        ]
                    }
                    if c.ports
                    else {}
                ),
            }
            for c in s.containers
        ]
    if s.node_name:
        spec["nn"] = s.node_name
    if s.node_selector:
        spec["sel"] = dict(s.node_selector)
    aff = _aff_to(s.affinity)
    if aff is not None:
        spec["af"] = aff
    if s.tolerations:
        spec["tol"] = [
            {
                "k": t.key,
                "o": t.operator,
                "v": t.value,
                "e": t.effect,
                **(
                    {"s": t.toleration_seconds}
                    if t.toleration_seconds is not None
                    else {}
                ),
            }
            for t in s.tolerations
        ]
    if s.topology_spread_constraints:
        spec["tsc"] = [
            {
                "ms": c.max_skew,
                "tk": c.topology_key,
                "wu": c.when_unsatisfiable,
                "s": _lsel_to(c.label_selector),
            }
            for c in s.topology_spread_constraints
        ]
    if s.priority:
        spec["pri"] = s.priority
    if s.priority_class_name:
        spec["pcn"] = s.priority_class_name
    if s.preemption_policy != "PreemptLowerPriority":
        spec["pp"] = s.preemption_policy
    if s.scheduler_name != "default-scheduler":
        spec["sn"] = s.scheduler_name
    if s.overhead:
        spec["ov"] = dict(s.overhead)
    if s.pod_group:
        spec["pg"] = s.pod_group
    if s.volumes:
        spec["vol"] = list(s.volumes)
    out = {"m": meta, "s": spec}
    if p.nominated_node_name:
        out["nom"] = p.nominated_node_name
    return out


def pod_from_state(d: dict) -> Pod:
    m = d.get("m", {})
    s = d.get("s", {})
    containers = tuple(
        Container(
            name=c.get("n", "main"),
            image=c.get("i", ""),
            requests=dict(c.get("r", {})),
            ports=tuple(
                ContainerPort(
                    container_port=pt.get("cp", 0),
                    host_port=pt.get("hp", 0),
                    protocol=pt.get("pr", "TCP"),
                    host_ip=pt.get("ip", ""),
                )
                for pt in c.get("p", ())
            ),
        )
        for c in s.get("c", ())
    )
    tolerations = tuple(
        Toleration(
            key=t.get("k", ""),
            operator=t.get("o", "Equal"),
            value=t.get("v", ""),
            effect=t.get("e", ""),
            toleration_seconds=t.get("s"),
        )
        for t in s.get("tol", ())
    )
    tsc = tuple(
        TopologySpreadConstraint(
            max_skew=c["ms"],
            topology_key=c["tk"],
            when_unsatisfiable=c["wu"],
            label_selector=_lsel_from(c.get("s", {})),
        )
        for c in s.get("tsc", ())
    )
    return Pod(
        metadata=ObjectMeta(
            name=m.get("n", ""),
            namespace=m.get("ns", "default"),
            uid=m.get("u", ""),
            labels=dict(m.get("l", {})),
            annotations=dict(m.get("a", {})),
            creation_timestamp=m.get("ct", 0.0),
        ),
        spec=PodSpec(
            containers=containers,
            node_name=s.get("nn", ""),
            node_selector=dict(s.get("sel", {})),
            affinity=_aff_from(s.get("af")),
            tolerations=tolerations,
            topology_spread_constraints=tsc,
            priority=s.get("pri", 0),
            priority_class_name=s.get("pcn", ""),
            preemption_policy=s.get("pp", "PreemptLowerPriority"),
            scheduler_name=s.get("sn", "default-scheduler"),
            overhead=dict(s.get("ov", {})),
            pod_group=s.get("pg", ""),
            volumes=tuple(s.get("vol", ())),
        ),
        nominated_node_name=d.get("nom", ""),
    )


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


def node_to_state(n: Node) -> dict:
    m = n.metadata
    meta: dict = {"n": m.name, "u": m.uid}
    if m.namespace != "default":
        # cluster-scoped in stock k8s, but virtual clusters own their
        # nodes: tenant identity rides the namespace (tenancy/)
        meta["ns"] = m.namespace
    if m.labels:
        meta["l"] = dict(m.labels)
    if m.creation_timestamp:
        meta["ct"] = m.creation_timestamp
    spec: dict = {}
    if n.spec.taints:
        spec["t"] = [
            {"k": t.key, "v": t.value, "e": t.effect} for t in n.spec.taints
        ]
    if n.spec.unschedulable:
        spec["u"] = True
    status: dict = {}
    if n.status.allocatable:
        status["a"] = dict(n.status.allocatable)
    if n.status.images:
        status["i"] = [
            {"n": list(i.names), "s": i.size_bytes} for i in n.status.images
        ]
    return {"m": meta, "s": spec, "st": status}


def node_from_state(d: dict) -> Node:
    m = d.get("m", {})
    s = d.get("s", {})
    st = d.get("st", {})
    return Node(
        metadata=ObjectMeta(
            name=m.get("n", ""),
            namespace=m.get("ns", "default"),
            uid=m.get("u", ""),
            labels=dict(m.get("l", {})),
            creation_timestamp=m.get("ct", 0.0),
        ),
        spec=NodeSpec(
            taints=tuple(
                Taint(t["k"], t.get("v", ""), t.get("e", "NoSchedule"))
                for t in s.get("t", ())
            ),
            unschedulable=bool(s.get("u", False)),
        ),
        status=NodeStatus(
            allocatable=dict(st.get("a", {})),
            images=tuple(
                ContainerImage(tuple(i.get("n", ())), i.get("s", 0))
                for i in st.get("i", ())
            ),
        ),
    )


# ---------------------------------------------------------------------------
# digest
# ---------------------------------------------------------------------------


def state_digest(queue, cache) -> str:
    """Canonical SHA-256 over the full durable state of a
    (SchedulingQueue, SchedulerCache) pair. Two instances with
    bit-identical logical state — tiers, attempt counts, backoff
    expiries, in-flight set, bound/assumed pods, TTL deadlines — hash
    equal; anything else does not. Tier entry ORDER is part of the
    digest on purpose: replay reproduces insertion order, so a restored
    standby drains pop_ready() in the same order the active would have."""
    blob = json.dumps(
        {"queue": queue.dump_state(), "cache": cache.dump_state()},
        sort_keys=True,
        separators=(",", ":"),
    ).encode()
    return hashlib.sha256(blob).hexdigest()
