"""Atomic whole-state snapshots that compact the write-ahead journal.

A snapshot file is the full durable state (queue tiers + cache) at a
journal cut, so restore = load snapshot + replay segments
`>= journal_from`. Format:

    [8s magic "TPUSSNP\\0"][u32 format_version][u32 crc32(payload)]
    [u32 payload_len][payload JSON]

Written crash-safely: temp file in the same directory, fsync, atomic
rename onto `snap-<journal_from>.snap`, fsync the directory. A crash
mid-write leaves only an ignorable temp file; a crash after rename has
the complete new snapshot. Older snapshots and the journal segments
they covered are pruned only after the new snapshot is durable.
"""

from __future__ import annotations

import os
import re
import struct
import zlib

from .journal import (
    FORMAT_VERSION,
    StateCorruption,
    StateVersionError,
)

SNAPSHOT_MAGIC = b"TPUSSNP\x00"
_HEAD = struct.Struct("<8sIII")  # magic, version, crc32(payload), len
_SNAP_RE = re.compile(r"^snap-(\d{8})\.snap$")

# json import deferred to call sites would save nothing; keep it simple
import json  # noqa: E402


def snapshot_path(directory: str, journal_from: int) -> str:
    return os.path.join(directory, f"snap-{journal_from:08d}.snap")


def snapshot_indices(directory: str) -> list[int]:
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    return sorted(
        int(m.group(1)) for m in (_SNAP_RE.match(n) for n in names) if m
    )


def write_snapshot(directory: str, payload: dict) -> tuple[str, int]:
    """Serialize + write the snapshot durably; returns (path, bytes).
    `payload["journal_from"]` names the first journal segment NOT
    compacted into this snapshot (the replay tail's start)."""
    body = json.dumps(payload, separators=(",", ":")).encode()
    head = _HEAD.pack(
        SNAPSHOT_MAGIC, FORMAT_VERSION, zlib.crc32(body), len(body)
    )
    final = snapshot_path(directory, int(payload["journal_from"]))
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(head)
        f.write(body)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    dfd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return final, len(head) + len(body)


def read_snapshot(path: str) -> dict:
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < _HEAD.size:
        raise StateCorruption(f"{path}: truncated snapshot header")
    magic, version, crc, length = _HEAD.unpack_from(blob, 0)
    if magic != SNAPSHOT_MAGIC:
        raise StateCorruption(f"{path}: bad snapshot magic {magic!r}")
    if version > FORMAT_VERSION:
        raise StateVersionError(
            f"{path}: snapshot format version {version} is newer than this "
            f"build supports (<= {FORMAT_VERSION}); refusing to restore"
        )
    body = blob[_HEAD.size : _HEAD.size + length]
    if len(body) != length or zlib.crc32(body) != crc:
        raise StateCorruption(
            f"{path}: snapshot payload fails CRC/length check "
            "(torn or corrupted write) — discard the state directory or "
            "restore from a replica"
        )
    return json.loads(body)


def read_latest_snapshot(directory: str) -> dict | None:
    """The newest snapshot, or None when the journal is all there is."""
    idxs = snapshot_indices(directory)
    if not idxs:
        return None
    return read_snapshot(snapshot_path(directory, idxs[-1]))


def prune_snapshots(directory: str, keep_from: int) -> int:
    """Delete snapshots older than the one at `keep_from`."""
    removed = 0
    for idx in snapshot_indices(directory):
        if idx < keep_from:
            try:
                os.unlink(snapshot_path(directory, idx))
                removed += 1
            except FileNotFoundError:
                pass
    return removed
