"""DurableState: wires the journal into a live queue/cache, restores,
snapshots, seals.

Lifecycle (cmd/main.py drives it):

    state = DurableState(state_dir, snapshot_interval_seconds=60)
    # Scheduler.__init__ calls:
    state.attach(queue, cache)      # restore snapshot+tail, then start
                                    # journaling every mutation
    # per cycle (Scheduler.schedule_cycle):
    state.maybe_snapshot()          # interval-gated compaction
    # SIGTERM:
    state.seal()                    # clean-shutdown snapshot + close

Restore exactness: every journal record carries the clock value `t` the
live mutation used; replay swaps the queue/cache clock for a replay
clock pinned to each record's `t` and re-executes the logical op, so
derived state (backoff expiries = t + backoff(attempts), TTL deadlines
= t + ttl, attempt counts from pop replay) is reproduced bit-identically
— the differential tests assert digest equality over randomized traces.

Snapshot consistency: the dump and the journal cut happen while holding
BOTH the queue and cache locks (lock order queue -> cache -> journal
buffer; no other code path takes two of these at once), so the cut is
an exact point in the op sequence — every op is either inside the
snapshot or in the replay tail, never both, never neither.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time as _time
from typing import Callable

from .codec import (
    node_from_state,
    pod_from_state,
)
from .journal import (
    BATCH_OP,
    Journal,
    StateCorruption,
    StateError,
    encode_batch_payload,
    iter_batch,
    replay_dir,
)
from .snapshot import (
    prune_snapshots,
    read_latest_snapshot,
    snapshot_indices,
    write_snapshot,
)

log = logging.getLogger("k8s_scheduler_tpu.state")


class _ReplayClock:
    """now() callable pinned to the journal record being replayed."""

    __slots__ = ("t",)

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class DurableState:
    def __init__(
        self,
        state_dir: str,
        *,
        snapshot_interval_seconds: float = 60.0,
        max_segment_bytes: int = 8 << 20,
        fsync: bool = True,
        metrics=None,  # SchedulerMetrics | None
        now: Callable[[], float] = _time.monotonic,
    ) -> None:
        self.dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        # compile-regime cache lifecycle rides the state dir: the
        # persistent executable cache (core/compile_cache.py) lives in
        # a sibling subtree so a standby that wins the lease inherits
        # the active's compiled programs along with its queue/cache
        # state. Path only — CompileCache.__init__ mkdirs when the
        # Scheduler actually wires it here (compileCacheDir may point
        # elsewhere or disable the cache, and an empty never-used
        # directory next to the journal would mislead restart triage).
        self.compile_cache_path = os.path.join(state_dir, "compile_cache")
        self.snapshot_interval = snapshot_interval_seconds
        self._now = now
        self._metrics = metrics
        # segment numbering floor: after a seal prunes every wal file,
        # a fresh journal must number from the snapshot's journal_from
        # upward or its records would sit below the restore tail
        snaps = snapshot_indices(state_dir)
        self.journal = Journal(
            state_dir,
            max_segment_bytes=max_segment_bytes,
            fsync=fsync,
            metrics=metrics,
            min_index=snaps[-1] if snaps else 0,
        )
        self._queue = None
        self._cache = None
        self._last_snapshot_at = now()
        self.last_snapshot: dict = {}
        self.last_restore: dict = {}
        # per-op Counter children memoized so the hot emit path does one
        # dict hit, not a labels() lookup
        self._append_counters: dict = {}
        # batch group-append state (see batch()): while a batch is open,
        # emissions from the OWNING thread buffer here and flush as ONE
        # journal record on exit. Lock order: _batch_lock is taken only
        # below the queue/cache instance locks (inside a mutator's emit)
        # or with neither held (batch exit) — never the other way, so it
        # cannot invert the queue -> cache order snapshot() relies on.
        self._batch_lock = threading.Lock()
        self._batch_owner: int | None = None
        self._batch_buf: list = []
        self._closed = False

    # ---- wiring ----------------------------------------------------------

    def attach(self, queue, cache) -> dict:
        """Restore whatever the state dir holds into (queue, cache), then
        start journaling their mutations. Returns the restore stats.
        Must run before the first scheduling cycle (the standby-takeover
        point in cmd/main.py: lease won -> Scheduler constructed ->
        attach -> first cycle)."""
        self._queue = queue
        self._cache = cache
        stats = self.restore_into(queue, cache)
        queue.set_journal(self._emit)
        cache.set_journal(self._emit)
        return stats

    def _emit(self, op: str, t: float, data: dict) -> None:
        if self._batch_owner is not None:  # racy pre-check; re-checked
            with self._batch_lock:
                owner = self._batch_owner
                if owner == threading.get_ident():
                    # the batch owner's emission: defer into the group
                    self._batch_buf.append((op, t, data))
                    return
                if owner is not None:
                    # a FOREIGN thread emitting while the serve thread's
                    # batch is open: flush the buffered prefix first so
                    # the journal keeps the true emission order (this
                    # record really did land after everything buffered
                    # so far — emits happen inside the mutators, in
                    # lock-acquisition order)
                    self._flush_batch_locked()
        self._append_record(op, t, data)

    def _append_record(self, op: str, t: float, data: dict) -> None:
        try:
            self.journal.append(op, t, data)
        except StateCorruption:
            raise
        except Exception as e:  # journal writer died (e.g. disk full):
            # durability is lost but serving must not be — detach the
            # emitters (degrade to the pre-durability stateless mode),
            # shout once, and keep the failure visible in status()
            log.error(
                "durable state DISABLED mid-run (%s); scheduler "
                "continues stateless — a takeover will restore only "
                "the last durable prefix", e,
            )
            # detach with PLAIN attribute stores, not set_journal(): the
            # caller holds one instance lock (we are inside a queue or
            # cache mutator) and taking the OTHER object's lock here
            # would invert the queue->cache order snapshot() relies on
            # (ABBA deadlock with a concurrent snapshot). An atomic ref
            # swap is all the readers need.
            if self._queue is not None:
                self._queue._journal = None
            if self._cache is not None:
                self._cache._journal = None
            self._closed = True  # schedlint: disable=TR001 -- monotonic latch: every writer stores True, readers tolerate one stale False (one extra append attempt on a dead writer); no lock needed for an idempotent one-way transition
            return
        m = self._metrics
        if m is not None:
            c = self._append_counters.get(op)
            if c is None:
                c = self._append_counters[op] = m.journal_appends.labels(
                    op=op
                )
            c.inc()

    # ---- batch group-append ----------------------------------------------

    def _flush_batch_locked(self) -> None:
        """Append the buffered batch as one record (callers hold
        _batch_lock). One buffered op degenerates to a plain record —
        same bytes a batchless emit would have written."""
        ops = self._batch_buf
        if not ops:
            return
        self._batch_buf = []  # schedlint: disable=TR001 -- every caller holds _batch_lock (documented contract in the docstring: _emit, batch() exit, snapshot, detach all take it first); the lint cannot see caller-held locks
        if len(ops) == 1:
            op, t, data = ops[0]
            self._append_record(op, t, data)
            return
        # the record's own t is the newest sub-op's clock; replay never
        # reads it (each sub-op carries its own t)
        self._append_record(BATCH_OP, ops[-1][1], encode_batch_payload(ops))
        m = self._metrics
        if m is not None and not self._closed:
            # keep per-logical-op append counters meaningful for folded
            # ops too (op="batch" counted once by _append_record above
            # is the record count; these are the logical-op counts)
            for op, _t, _d in ops:
                c = self._append_counters.get(op)
                if c is None:
                    c = self._append_counters[op] = (
                        self._metrics.journal_appends.labels(op=op)
                    )
                c.inc()

    @contextlib.contextmanager
    def batch(self):
        """Group-append scope for the vectorized apply/bind fold: every
        journal emission from the CALLING thread inside the scope
        coalesces into ONE batch record, appended on exit — one record,
        one buffer push, one share of the group-commit fsync per cycle
        instead of N. Replay expands the batch with each sub-op's own
        clock value, so restored state is bit-identical to N single
        records (tests/test_state_journal.py asserts the digests).

        Emissions from OTHER threads (informer/admission paths) while a
        batch is open first flush the buffered prefix, preserving true
        emission order. Re-entrant and closed-state safe: a nested or
        detached batch() is a transparent no-op."""
        tid = threading.get_ident()
        with self._batch_lock:
            mine = self._batch_owner is None and not self._closed
            if mine:
                self._batch_owner = tid
        try:
            yield
        finally:
            if mine:
                with self._batch_lock:
                    try:
                        self._flush_batch_locked()
                    finally:
                        self._batch_owner = None

    # ---- restore ---------------------------------------------------------

    def restore_into(self, queue, cache) -> dict:
        """Load the latest snapshot (if any) and replay the journal tail,
        leaving (queue, cache) in the exact pre-crash state. Journaling
        and metrics observers are suppressed during replay — a restore
        must not re-journal itself or inflate intake counters."""
        t0 = _time.perf_counter()
        snap = read_latest_snapshot(self.dir)
        from_idx = 0
        clean = False
        if snap is not None:
            queue.load_state(snap["queue"])
            cache.load_state(snap["cache"])
            from_idx = int(snap["journal_from"])
            clean = bool(snap.get("clean_shutdown", False))
        clock = _ReplayClock()
        saved = (
            queue._now, cache._now,
            queue._journal, cache._journal,
            queue._on_enqueue,
        )
        queue._now = cache._now = clock
        queue._journal = cache._journal = None
        queue._on_enqueue = lambda q, e: None
        replayed = 0
        try:
            for op, t, data in replay_dir(self.dir, from_idx):
                if op == BATCH_OP:
                    # expand the group-append: each sub-op replays under
                    # ITS OWN clock value, exactly as N singles would
                    for sub_op, sub_t, sub_d in iter_batch(data):
                        clock.t = sub_t
                        self._apply(queue, cache, sub_op, sub_d)
                    replayed += 1
                    continue
                clock.t = t
                self._apply(queue, cache, op, data)
                replayed += 1
        finally:
            (
                queue._now, cache._now,
                queue._journal, cache._journal,
                queue._on_enqueue,
            ) = saved
        seconds = _time.perf_counter() - t0
        self.last_restore = {
            "snapshot": snap is not None,
            "clean_shutdown": clean,
            "journal_from": from_idx,
            "records_replayed": replayed,
            "seconds": round(seconds, 6),
            "pending": dict(queue.pending_counts()),
            "cache": dict(cache.counts()),
        }
        m = self._metrics
        if m is not None:
            m.restore_records.set(replayed)
            m.restore_duration.set(seconds)
        if snap is not None or replayed:
            log.info(
                "durable state restored: snapshot=%s replayed=%d records "
                "in %.3fs (pending=%s cache=%s)",
                snap is not None, replayed, seconds,
                self.last_restore["pending"], self.last_restore["cache"],
            )
        return self.last_restore

    @staticmethod
    def _apply(queue, cache, op: str, d: dict) -> None:
        """Re-execute one logical mutation. Unknown ops are refused —
        they mean the journal was written by a newer build whose ops
        this one cannot reproduce."""
        if op == "q.add":
            queue.add(pod_from_state(d["pod"]))
        elif op == "q.update":
            queue.update(pod_from_state(d["pod"]))
        elif op == "q.delete":
            queue.delete(d["uid"])
        elif op == "q.pop":
            queue.pop_ready(hold=bool(d.get("hold")))
        elif op == "q.unsched":
            queue.requeue_unschedulable(
                pod_from_state(d["pod"]), reasons=tuple(d.get("reasons", ()))
            )
        elif op == "q.backoff":
            queue.requeue_backoff(
                pod_from_state(d["pod"]), event=d.get("event", "BindError")
            )
        elif op == "q.flush_backoff":
            queue.flush_backoff()
        elif op == "q.flush_timeout":
            queue.flush_unschedulable_timeout()
        elif op == "q.move":
            queue.move_all_to_active_or_backoff(d["event"])
        elif op == "q.recover":
            queue.recover_in_flight()
        elif op == "q.retire":
            queue.retire_in_flight(d["uids"])
        elif op == "c.add_node":
            cache.add_node(node_from_state(d["node"]))
        elif op == "c.update_node":
            cache.update_node(node_from_state(d["node"]))
        elif op == "c.remove_node":
            cache.remove_node(d["name"])
        elif op == "c.add_pod":
            cache.add_pod(pod_from_state(d["pod"]), d["node"])
        elif op == "c.remove_pod":
            cache.remove_pod(d["uid"])
        elif op == "c.assume":
            cache.assume(pod_from_state(d["pod"]), d["node"])
        elif op == "c.finish_binding":
            cache.finish_binding(d["uid"])
        elif op == "c.confirm":
            cache.confirm(d["uid"])
        elif op == "c.forget":
            cache.forget(d["uid"])
        elif op == "c.expire":
            cache.cleanup_expired()
        else:
            raise StateCorruption(
                f"unknown journal op {op!r} — written by a newer build? "
                "(same format version, unrecognized operation)"
            )

    # ---- snapshots -------------------------------------------------------

    def maybe_snapshot(self) -> bool:
        """Interval-gated snapshot; the Scheduler calls this once per
        cycle (off the per-profile hot path)."""
        if self.snapshot_interval <= 0 or self._closed:
            return False
        if self._now() - self._last_snapshot_at < self.snapshot_interval:
            return False
        self.snapshot()
        return True

    def snapshot(self, clean_shutdown: bool = False) -> str:
        """Dump queue+cache at a journal cut, write durably, prune the
        compacted segments and older snapshots."""
        if self._queue is None or self._cache is None:
            raise StateCorruption("snapshot before attach()")
        t0 = _time.perf_counter()
        # consistent cut: both state locks held across dump + cut (see
        # module docstring for the lock-order argument)
        with self._queue._lock:
            with self._cache._lock:
                # flush any open batch prefix first: its mutations are
                # already applied (hence inside the dump below) and the
                # flush lands their record BEFORE the cut — otherwise a
                # post-cut batch record would replay ops the snapshot
                # already contains (double-apply). The emitters are
                # blocked on the two locks we hold, so nothing new can
                # buffer between this flush and the cut.
                with self._batch_lock:
                    self._flush_batch_locked()
                qstate = self._queue.dump_state()
                cstate = self._cache.dump_state()
                tail_from = self.journal.cut()
                t_mono = (
                    self._queue._now()
                    if callable(self._queue._now) else _time.monotonic()
                )
        payload = {
            "format_version": 1,
            "taken_mono": t_mono,
            "taken_wall": _time.time(),
            "clean_shutdown": bool(clean_shutdown),
            "journal_from": tail_from,
            "queue": qstate,
            "cache": cstate,
        }
        path, nbytes = write_snapshot(self.dir, payload)
        # drain the writer before pruning: records for pre-cut segments
        # may still sit in its buffer, and pruning first would let it
        # recreate a just-deleted segment file (harmless for restore —
        # the snapshot covers those ops — but it leaks stale segments
        # and skews the segment gauge). A dead writer skips the barrier:
        # nothing will be written, pruning is safe.
        try:
            self.journal.flush()
        except StateError:
            pass
        # only after the snapshot is durable may its inputs disappear
        self.journal.prune(tail_from)
        prune_snapshots(self.dir, tail_from)
        seconds = _time.perf_counter() - t0
        self._last_snapshot_at = self._now()  # schedlint: disable=TR001 -- httpserver reaches snapshot() only through the by-name fallback on 'snapshot' (the debug routes call FlightRecorder.snapshot); the sole real caller is the serve loop via maybe_snapshot/seal
        self.last_snapshot = {  # schedlint: disable=TR001 -- same fallback inventory as the line above; single-writer in practice
            "path": path,
            "bytes": nbytes,
            "journal_from": tail_from,
            "seconds": round(seconds, 6),
            "clean_shutdown": bool(clean_shutdown),
        }
        m = self._metrics
        if m is not None:
            m.snapshot_writes.inc()
            m.snapshot_duration.observe(seconds)
            m.snapshot_bytes.set(nbytes)
        return path

    def ack_barrier(self, timeout: float = 10.0) -> bool:
        """WAL-before-ack durability barrier (service/admission.py):
        block until every journal record appended so far — in
        particular the q.add records the caller just emitted — is
        fsynced, sharing the writer's group commit with every other
        waiter. Returns False when durability is off or already lost
        (sealed, detached, or the writer died): the ack then goes out
        with `durable: false` instead of blocking on a dead journal."""
        if self._closed or self.journal.failed is not None:
            return False
        try:
            self.journal.flush(timeout=timeout, upto=self.journal.seq())
        except StateError:
            return False
        return True

    def flush_seq(self) -> int:
        """The journal's current append sequence — the group-commit
        flush seq a just-returned ack_barrier rode. Stamped as the
        `flush_seq` attr on ack.barrier trace spans (core/spans) so
        concurrent submitters that shared one fsync are visibly joined
        to it."""
        return self.journal.seq()

    def detach(self) -> None:
        """Stop journaling: drop the queue/cache emitters (plain
        attribute stores — see _emit for the lock-order argument) and
        mark this state closed. Used by the degradation ladder's
        `stateless` rung after seal(): the process keeps serving with
        no durability, and the sealed snapshot is what a standby
        restores."""
        with self._batch_lock:
            self._flush_batch_locked()
            self._batch_owner = None
        if self._queue is not None:
            self._queue._journal = None
        if self._cache is not None:
            self._cache._journal = None
        self._closed = True  # schedlint: disable=TR001 -- monotonic latch (see _append_record): idempotent one-way True store

    def seal(self) -> None:
        """Clean shutdown: final snapshot (so the next start replays
        nothing), flush, close. Safe to call twice."""
        if self._closed:
            return
        self._closed = True  # schedlint: disable=TR001 -- monotonic latch (see _append_record): idempotent one-way True store
        try:
            if self._queue is not None and self.journal.failed is None:
                self.snapshot(clean_shutdown=True)
        finally:
            try:
                self.journal.flush()
            except StateError:
                pass  # writer already dead; close() still joins it
            self.journal.close()

    # ---- observability ---------------------------------------------------

    def status(self) -> dict:
        """The /debug/state payload."""
        out = {
            "state_dir": self.dir,
            "snapshot_interval_s": self.snapshot_interval,
            "journal": self.journal.status(),
            "last_snapshot": dict(self.last_snapshot),
            "last_restore": dict(self.last_restore),
            "sealed": self._closed,
        }
        cc = getattr(self, "compile_cache", None)
        if cc is not None:
            # the Scheduler pins its CompileCache here after wiring so
            # /debug/state shows hit/miss/entry counts next to the
            # journal the same directory holds
            out["compile_cache"] = cc.status()
        deg = getattr(self, "degradation", None)
        if deg is not None:
            # the Scheduler pins its DegradationLadder here: the current
            # rung belongs next to the durability it can seal away —
            # plus the full transition ring (wall-timestamped), so MTTR
            # is computable over HTTP instead of from logs
            out["degradation"] = deg.status()
            out["degradation"]["transition_log"] = deg.transition_log()
        shard = getattr(self, "sharding", None)
        if shard is not None:
            # the Scheduler pins its mesh layout + per-profile
            # collective-payload probe here (same pattern): operators
            # triaging cross-device traffic read it off /debug/state
            out["sharding"] = {
                k: (dict(v) if isinstance(v, dict) else v)
                for k, v in shard.items()
            }
        return out
