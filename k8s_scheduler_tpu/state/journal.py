"""Write-ahead journal: checksummed records, rotated segments, group fsync.

Wire format (little-endian). Every segment file starts with a fixed
16-byte header:

    [8s magic "TPUSWAL\\0"][u32 format_version][u32 crc32(magic+version)]

followed by length-prefixed records:

    [u32 payload_len][u32 crc32(payload)][payload]

where payload is compact JSON `{"op": str, "t": float, "d": {...}}` —
`t` is the emitting clock (CLOCK_MONOTONIC) value the mutation used, so
replay can re-execute the operation under a replay clock and reproduce
backoff expiries / TTL deadlines exactly.

Append path: `append()` pushes the UNENCODED (op, t, payload) onto an
in-memory buffer — no JSON, no CRC, no I/O, no fsync; just a deque
append under the buffer condition variable (~5us with a pod payload,
dominated by building the payload dict itself). This is safe because
every payload dict is built fresh at emit time (state/codec converters)
and never mutated afterwards. A dedicated writer thread drains the
buffer, encodes, writes each batch with ordinary buffered writes, and
issues ONE fsync per drained batch (group commit) — mirroring how the
serving pipeline keeps only decision bytes synchronous. `flush()` is
the durability barrier (blocks until everything appended so far is
fsynced).

Segments rotate at `max_segment_bytes`, and `cut()` rotates on demand
for snapshot compaction: it returns the index of the first segment that
will hold post-cut records, so a snapshot taken at the cut replays
exactly the tail `>= cut`. A crashed process's torn final record is
detected by length/CRC at replay and discarded — never partially
applied; a segment whose tail is torn simply ends there (the records
after a torn tail were never acknowledged as durable). A segment
written by a FUTURE format version is refused with a clear error
instead of being misparsed.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import struct
import sys
import threading
import time as _time
import zlib

log = logging.getLogger("k8s_scheduler_tpu.state")


def _fault_hook(point: str) -> None:
    """Fault-injection bridge (core/faults.py) without importing the
    core package: resolved through sys.modules, so a restore-only
    Journal (standby, tooling, tests) never drags jax in — arming
    requires the faults module to be imported already, and unarmed cost
    is one dict lookup per writer batch (never the append path)."""
    mod = sys.modules.get("k8s_scheduler_tpu.core.faults")
    if mod is not None and mod.ARMED:
        mod.raise_enospc(point)

SEGMENT_MAGIC = b"TPUSWAL\x00"
FORMAT_VERSION = 1

_HEADER = struct.Struct("<8sI")  # magic, version (crc32 of these follows)
_CRC = struct.Struct("<I")
_FRAME = struct.Struct("<II")  # payload_len, crc32(payload)
_SEG_RE = re.compile(r"^wal-(\d{8})\.seg$")


class StateError(RuntimeError):
    """Base error for the durable-state layer."""


class StateCorruption(StateError):
    """Non-torn-tail damage: bad magic, unknown op, unreadable snapshot."""


class StateVersionError(StateError):
    """Journal/snapshot written by a newer format version than this build."""


def segment_header(version: int = FORMAT_VERSION) -> bytes:
    body = _HEADER.pack(SEGMENT_MAGIC, version)
    return body + _CRC.pack(zlib.crc32(body))


def encode_record(op: str, t: float, data: dict) -> bytes:
    payload = json.dumps(
        {"op": op, "t": t, "d": data}, separators=(",", ":")
    ).encode()
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


# Batch record: ONE journal record carrying N logical sub-operations —
# the group-append the vectorized apply/bind fold emits per cycle
# (core/scheduler._apply_phase under DurableState.batch()). The wire
# shape is an ordinary record whose op is BATCH_OP and whose payload is
# {"ops": [[op, t, d], ...]}: each sub-op keeps its OWN clock value, so
# replay pins the replay clock per sub-record and reproduces the exact
# state N single records would (the digest-equivalence contract
# tests/test_state_journal.py asserts). Because the batch is one frame,
# a crash tears it ATOMICALLY — a torn tail discards the whole cycle's
# fold, never a prefix of it (the per-record CRC covers all sub-ops).
BATCH_OP = "batch"

# Tenancy journal ops (tenancy/registry.py): a TenantRegistry journals
# every virtual-cluster mutation — lifecycle (create/suspend/resume/
# delete), membership (node/pod adds, removals), and binds — under
# "tn."-prefixed ops into its OWN Journal directory, using this exact
# wire format and the same emit-once clock discipline (JE001-003).
# The streams never mix by construction: DurableState.restore_into
# refuses unknown ops, and restore_registry refuses non-tn ops, so a
# misconfigured shared directory fails loudly on the first replay
# instead of silently cross-applying records.
TENANCY_OP_PREFIX = "tn."
TENANCY_OPS = (
    "tn.create", "tn.suspend", "tn.resume", "tn.delete",
    "tn.node", "tn.pod", "tn.unpod", "tn.bind",
)


def encode_batch_payload(ops: list) -> dict:
    """Payload dict for a batch record from [(op, t, data), ...]."""
    return {"ops": [[op, t, data] for op, t, data in ops]}


def iter_batch(data: dict):
    """Yield (op, t, data) sub-records of a batch record's payload —
    the replay-side inverse of encode_batch_payload (used by
    DurableState.restore_into and the state tooling)."""
    for op, t, d in data.get("ops", ()):
        yield op, t, d or {}


def segment_path(directory: str, index: int) -> str:
    return os.path.join(directory, f"wal-{index:08d}.seg")


def segment_indices(directory: str) -> list[int]:
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    out = []
    for n in names:
        m = _SEG_RE.match(n)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def read_segment(path: str):
    """Yield (op, t, data) records from one segment. A torn tail (short
    frame, short payload, or CRC mismatch on the FINAL record of a
    crashed writer) ends iteration cleanly — the torn bytes were never
    acknowledged durable, so discarding them is the correct replay. A
    wrong magic raises StateCorruption; a future format version raises
    StateVersionError (replaying guesses against an unknown format is
    how state gets silently mangled)."""
    with open(path, "rb") as f:
        blob = f.read()
    hsize = _HEADER.size + _CRC.size
    if len(blob) < hsize:
        # header itself torn: the segment was created but nothing ever
        # became durable in it
        return
    magic, version = _HEADER.unpack_from(blob, 0)
    (crc,) = _CRC.unpack_from(blob, _HEADER.size)
    if magic != SEGMENT_MAGIC:
        raise StateCorruption(
            f"{path}: bad segment magic {magic!r} (not a journal segment)"
        )
    if crc != zlib.crc32(blob[: _HEADER.size]):
        # torn header write: treat as an empty segment
        return
    if version > FORMAT_VERSION:
        raise StateVersionError(
            f"{path}: journal format version {version} is newer than this "
            f"build supports (<= {FORMAT_VERSION}); refusing to replay — "
            "upgrade the scheduler or discard the state directory"
        )
    off = hsize
    n = len(blob)
    while True:
        if off + _FRAME.size > n:
            if off < n:
                log.warning(
                    "%s: torn frame header at byte %d discarded", path, off
                )
            return  # torn frame header at EOF
        length, crc = _FRAME.unpack_from(blob, off)
        start = off + _FRAME.size
        end = start + length
        if end > n:
            log.warning(
                "%s: torn final record at byte %d discarded", path, off
            )
            return  # torn payload at EOF
        payload = blob[start:end]
        if zlib.crc32(payload) != crc:
            if end < n:
                # a crash tear can only sit at EOF (every batch is
                # fsynced before it is acknowledged, and a segment is
                # synced before rotation opens the next): a bad record
                # FOLLOWED BY MORE BYTES is real damage to acknowledged
                # data — refuse to replay a stream with a hole in it
                raise StateCorruption(
                    f"{path}: record at byte {off} fails CRC with "
                    f"{n - end} bytes following — mid-segment "
                    "corruption of acknowledged records; restore from "
                    "a replica or discard the state directory"
                )
            log.warning(
                "%s: torn final record at byte %d discarded", path, off
            )
            return  # torn tail: discard, never partially apply
        rec = json.loads(payload)
        yield rec["op"], rec["t"], rec.get("d") or {}
        off = end


def replay_dir(directory: str, from_index: int = 0):
    """Yield (op, t, data) across all segments >= from_index, in order."""
    for idx in segment_indices(directory):
        if idx < from_index:
            continue
        yield from read_segment(segment_path(directory, idx))


class Journal:
    """The append side: buffered records, writer thread, group fsync.

    A restarted process never appends into an old segment (whose tail
    may be torn): construction allocates a fresh segment index past
    everything on disk, and replay handles old torn tails read-side.
    """

    def __init__(
        self,
        directory: str,
        *,
        max_segment_bytes: int = 8 << 20,
        fsync: bool = True,
        metrics=None,  # SchedulerMetrics | None
        min_index: int = 0,
    ) -> None:
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        existing = segment_indices(directory)
        self._cond = threading.Condition()
        self._buf: collections.deque = collections.deque()
        # the index current appends are destined for; its file is opened
        # lazily by the writer on the first record. Indices in the buffer
        # are monotonic (assigned under the cond at append; bumped under
        # the cond by cut() and by the writer's size rotation), so the
        # FIFO writer never switches back to an older segment.
        # `min_index` is the floor the OWNER derives from the newest
        # snapshot's journal_from: after a seal prunes every wal file,
        # numbering must NOT restart at 0 below the snapshot — restore
        # replays only segments >= journal_from, so records written
        # under a lower index would be silently skipped forever.
        self._cur_index = max(
            (existing[-1] + 1) if existing else 0, min_index
        )
        self._cur_count = 0
        self._max = max_segment_bytes
        self._appended = 0
        self._durable = 0
        self._stopped = False
        # set when the writer thread dies on an I/O error (ENOSPC, EIO):
        # durability is over for this Journal — append()/flush() raise so
        # the owner (DurableState._emit) can degrade loudly instead of
        # buffering into an unbounded, never-drained deque
        self.failed: str | None = None
        # writer poll cadence / forced-wake depth (see append())
        self._poll_s = 0.02
        self._wake_depth = 4096
        self._do_fsync = fsync
        self._metrics = metrics
        self._fh = None
        self._open_index: int | None = None
        self._open_bytes = 0
        self.bytes_written = 0
        self.last_fsync_s = 0.0
        self.fsync_count = 0
        # the writer thread starts LAZILY on the first append: a
        # restore-only Journal (standbys before attach, tooling reading
        # the dir, tests) must not leave a polling thread behind
        self._writer: threading.Thread | None = None

    # ---- append path (the hot side: no I/O) -----------------------------

    def append(self, op: str, t: float, data: dict) -> int:
        """Buffer one record; returns its sequence number. Never blocks
        on disk and never encodes — JSON+CRC framing happens on the
        writer thread (durability via flush(), the explicit barrier).
        `data` must be a freshly built dict the caller will not mutate
        (the state/codec converters guarantee this)."""
        with self._cond:
            if self._stopped:
                raise StateError("journal is closed")
            if self.failed is not None:
                raise StateError(f"journal writer failed: {self.failed}")
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._run, name="journal-writer", daemon=True
                )
                self._writer.start()
            self._buf.append((self._cur_index, op, t, data))
            self._cur_count += 1
            self._appended += 1
            seq = self._appended
            # do NOT notify per record: waking the writer mid-burst makes
            # it encode concurrently with the scheduling thread and the
            # GIL contention lands on the bind path (measured ~4x the
            # append cost). The writer polls on a short timeout instead,
            # so encoding happens while the scheduler waits on device
            # transfers (GIL released). Only a deep buffer forces a wake.
            if len(self._buf) >= self._wake_depth:
                self._cond.notify()
        return seq

    def cut(self) -> int:
        """Rotate so that every record appended from now on lands in a
        new segment; returns that segment's index — the snapshot's
        `journal_from`. The caller must hold whatever locks stop
        concurrent emitters (DurableState.snapshot holds the queue and
        cache locks), so the cut is a consistent point in the op
        sequence."""
        with self._cond:
            if self._cur_count:
                self._cur_index += 1
                self._cur_count = 0
            return self._cur_index

    def flush(
        self, timeout: float | None = 30.0, upto: int | None = None
    ) -> None:
        """Durability barrier: returns once everything appended before
        the call has been written and fsynced. `upto` narrows the
        barrier to a specific append sequence (the value a prior
        `append()` returned) — the WAL-before-ack path in
        service/admission.py waits only for ITS records, so concurrent
        submitters share one group-commit fsync instead of serializing
        behind each other's tails."""
        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._cond:
            target = self._appended if upto is None else min(
                upto, self._appended
            )
            self._cond.notify()  # expedite past the writer's poll cadence
            while self._durable < target:
                if self.failed is not None:
                    raise StateError(
                        f"journal writer failed: {self.failed}"
                    )
                if self._stopped and not self._buf:
                    return
                remaining = None
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise StateError(
                            f"journal flush timed out ({target - self._durable}"
                            " records undrained)"
                        )
                self._cond.wait(remaining)

    def seq(self) -> int:
        """Sequence number of the newest append so far — the `upto`
        target a caller passes to flush() to wait for exactly the
        records it just emitted."""
        with self._cond:
            return self._appended

    def close(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._writer is not None:
            self._writer.join(timeout=30)
            if self._writer.is_alive():
                # writer wedged on a stalled disk: do NOT touch the
                # file object it may still be writing to — closing it
                # under the writer would tear a record mid-frame. The
                # fd leaks with the (daemon) thread; the segment's torn
                # tail is handled at the next replay.
                log.error(
                    "journal writer failed to drain within 30s at "
                    "close; leaving its segment open (torn tail will "
                    "be discarded at next restore)"
                )
                return
            self._writer = None
        if self._fh is not None:
            self._sync_open()
            self._fh.close()
            self._fh = None

    def prune(self, before_index: int) -> int:
        """Delete segments wholly superseded by a durable snapshot."""
        removed = 0
        for idx in segment_indices(self.dir):
            if idx < before_index:
                try:
                    os.unlink(segment_path(self.dir, idx))
                    removed += 1
                except FileNotFoundError:
                    pass
        self._note_segments()
        return removed

    def status(self) -> dict:
        with self._cond:
            buffered = len(self._buf)
            appended = self._appended
            durable = self._durable
            cur = self._cur_index
        return {
            "segments": len(segment_indices(self.dir)),
            "current_segment": cur,
            "failed": self.failed,
            "appended": appended,
            "durable": durable,
            "buffered": buffered,
            "bytes_written": self.bytes_written,
            "last_fsync_ms": round(self.last_fsync_s * 1e3, 3),
            "fsync_count": self.fsync_count,
            "fsync": self._do_fsync,
        }

    # ---- writer thread ---------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._buf and not self._stopped:
                    self._cond.wait(self._poll_s)
                    if self._buf or self._stopped:
                        break
                batch = list(self._buf)
                self._buf.clear()
                stopped = self._stopped
            if batch:
                try:
                    self._write_batch(batch)
                except Exception as e:
                    # I/O failure (ENOSPC, EIO, ...): durability cannot
                    # be promised any further — fail LOUDLY and
                    # permanently rather than buffering forever or
                    # risking duplicate records from blind retries of a
                    # possibly-partially-written batch (replay exactness
                    # beats best-effort persistence here)
                    log.exception(
                        "journal writer died; durability disabled "
                        "(%d records lost from this batch, %d still "
                        "buffered)", len(batch), len(self._buf),
                    )
                    try:
                        if self._fh is not None:
                            self._fh.close()
                    except OSError:
                        pass
                    self._fh = None
                    with self._cond:
                        self.failed = f"{type(e).__name__}: {e}"
                        self._cond.notify_all()
                    return
                with self._cond:
                    self._durable += len(batch)
                    self._cond.notify_all()
                m = self._metrics
                if m is not None:
                    m.journal_buffer.set(len(self._buf))
            if stopped and not batch:
                return

    def _write_batch(self, batch: list[tuple[int, str, float, dict]]) -> None:
        # `journal_enospc` injection point: raises ENOSPC exactly where
        # a full disk would, driving the real writer-death path (_run's
        # handler -> failed flag -> DurableState degrades to stateless)
        _fault_hook("journal_enospc")
        wrote = 0
        for idx, op, t, data in batch:
            rec = encode_record(op, t, data)
            if idx != self._open_index:
                if self._fh is not None:
                    self._sync_open()
                    self._fh.close()
                self._fh = open(segment_path(self.dir, idx), "ab")
                if self._fh.tell() == 0:
                    self._fh.write(segment_header())
                self._open_index = idx
                self._open_bytes = 0
                self._note_segments()
            self._fh.write(rec)
            self._open_bytes += len(rec)
            wrote += len(rec)
        if self._fh is not None:
            self._sync_open()
        if self._open_bytes > self._max:
            # size rotation, decided writer-side with REAL byte counts:
            # bump the append index so the next record opens a fresh
            # segment (unless a cut already bumped past us)
            with self._cond:
                if self._cur_index == self._open_index:
                    self._cur_index += 1
                    self._cur_count = 0
        self.bytes_written += wrote
        m = self._metrics
        if m is not None:
            m.journal_bytes.inc(wrote)

    def _sync_open(self) -> None:
        """One flush+fsync for everything written since the last sync —
        the group-commit point (runs ONLY on the writer thread)."""
        self._fh.flush()
        if not self._do_fsync:
            return
        t0 = _time.perf_counter()
        os.fsync(self._fh.fileno())
        self.last_fsync_s = _time.perf_counter() - t0
        self.fsync_count += 1
        m = self._metrics
        if m is not None:
            m.journal_fsync.observe(self.last_fsync_s)

    def _note_segments(self) -> None:
        m = self._metrics
        if m is not None:
            m.journal_segments.set(len(segment_indices(self.dir)))
