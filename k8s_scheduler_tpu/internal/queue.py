"""SchedulingQueue: active / backoff / unschedulable tiers.

The reference's `PriorityQueue` (`internal/queue/scheduling_queue.go` —
[UNVERIFIED], mount empty; SURVEY.md §2 C3) is a heap popped one pod at a
time by 16-way goroutine consumers. The TPU design schedules the WHOLE
ready set per cycle, so the heap collapses to set bookkeeping:

- `active`: pods ready for the next cycle. `pop_ready()` drains it (the
  batch analogue of Pop); ordering is re-derived by the encoder's
  `pod_order` (PrioritySort), so no heap is needed host-side.
- `backoff`: pods that failed recently, with an expiry deadline
  (exponential per-pod backoff, initial/max from config — upstream
  podInitialBackoffSeconds/podMaxBackoffSeconds). `flush_backoff()` moves
  expired entries back to active (upstream's flushBackoffQCompleted).
- `unschedulable`: pods that found no node and wait for a cluster event.
  `move_all_to_active_or_backoff(event)` relocates them (upstream
  MoveAllToActiveOrBackoffQueue on informer events), honoring the
  event→plugin queueing-hint table below.

Pods handed out by `pop_ready()` are tracked as in-flight until the cycle
requeues or drops them; a delete arriving mid-cycle marks the uid so the
requeue discards it instead of resurrecting a deleted pod. All public
methods take the queue lock — informer callbacks may run on other threads
than the scheduling loop (same discipline as SchedulerCache).

Time is injected (`now` callable) so tests drive the clock.
"""

from __future__ import annotations

import dataclasses
import threading
import time as _time
from typing import Callable, Iterable, Sequence

from ..models.api import Pod

# Cluster events (the reference's framework.ClusterEvent resource/action
# pairs, collapsed to the ones that matter for requeueing).
EVENT_NODE_ADD = "NodeAdd"
EVENT_NODE_UPDATE = "NodeUpdate"
EVENT_NODE_DELETE = "NodeDelete"
EVENT_POD_ADD = "PodAdd"
EVENT_POD_UPDATE = "PodUpdate"
EVENT_POD_DELETE = "PodDelete"
EVENT_PVC_CHANGE = "PvcChange"  # PVC add/update (e.g. became bound)
EVENT_PV_CHANGE = "PvChange"  # PV add/update (e.g. became available)
EVENT_STORAGE_CLASS_CHANGE = "StorageClassChange"
EVENT_UNSCHEDULABLE_TIMEOUT = "UnschedulableTimeout"

# Which failure reasons (plugin names) an event can unstick — the
# queueing-hint registry (upstream EventsToRegister). A pod rejected by
# plugin X only requeues on events in HINTS[X]. Unknown reasons requeue on
# everything (conservative default, matches hintless upstream behavior).
QUEUEING_HINTS: dict[str, frozenset[str]] = {
    "NodeResourcesFit": frozenset(
        {EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_POD_DELETE}
    ),
    "NodeAffinity": frozenset({EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "NodeName": frozenset({EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "NodeUnschedulable": frozenset({EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "TaintToleration": frozenset({EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "NodePorts": frozenset({EVENT_NODE_ADD, EVENT_POD_DELETE}),
    "InterPodAffinity": frozenset(
        {EVENT_NODE_ADD, EVENT_POD_ADD, EVENT_POD_UPDATE, EVENT_POD_DELETE}
    ),
    "PodTopologySpread": frozenset(
        {EVENT_NODE_ADD, EVENT_POD_ADD, EVENT_POD_UPDATE, EVENT_POD_DELETE}
    ),
    "Coscheduling": frozenset({EVENT_POD_ADD, EVENT_POD_DELETE,
                               EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "VolumeBinding": frozenset({
        EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_PVC_CHANGE,
        EVENT_PV_CHANGE, EVENT_STORAGE_CLASS_CHANGE,
    }),
}


@dataclasses.dataclass
class _QueuedPod:
    pod: Pod
    attempts: int = 0  # scheduling attempts so far (drives backoff length)
    backoff_expiry: float = 0.0
    # plugins that rejected it (() = unknown -> requeue on any event). A pod
    # requeues when the event can cure ANY of its reasons (upstream: the
    # union of the failed plugins' EventsToRegister hints).
    unschedulable_reasons: tuple[str, ...] = ()
    enqueued_at: float = 0.0


class SchedulingQueue:
    def __init__(
        self,
        initial_backoff_seconds: float = 1.0,
        max_backoff_seconds: float = 10.0,
        unschedulable_timeout_seconds: float = 300.0,
        now: Callable[[], float] = _time.monotonic,
        on_enqueue: Callable[[str, str], None] | None = None,
    ) -> None:
        self._initial = initial_backoff_seconds
        self._max = max_backoff_seconds
        self._timeout = unschedulable_timeout_seconds
        self._now = now
        # (queue_name, event) observer for EVERY tier entry — feeds the
        # upstream scheduler_queue_incoming_pods_total metric; kept in the
        # queue so no transition undercounts
        self._on_enqueue = on_enqueue or (lambda queue, event: None)
        self._lock = threading.RLock()
        self._active: dict[str, _QueuedPod] = {}
        self._backoff: dict[str, _QueuedPod] = {}
        self._unschedulable: dict[str, _QueuedPod] = {}
        self._in_flight: dict[str, _QueuedPod] = {}
        self._deleted_in_flight: set[str] = set()

    # ---- intake ----------------------------------------------------------

    def add(self, pod: Pod) -> None:
        """New pod (informer Add): straight to active."""
        with self._lock:
            uid = pod.uid
            self._backoff.pop(uid, None)
            self._unschedulable.pop(uid, None)
            self._active[uid] = _QueuedPod(pod, enqueued_at=self._now())
            self._on_enqueue("active", EVENT_POD_ADD)

    def update(self, pod: Pod) -> None:
        """Spec/labels changed: an update can unstick its own pod."""
        with self._lock:
            uid = pod.uid
            for tier in (self._active, self._backoff, self._unschedulable):
                if uid in tier:
                    entry = tier[uid]
                    entry.pod = pod
                    if tier is self._unschedulable:
                        # the update may cure the failure, but the pod's
                        # backoff window still applies (upstream checks
                        # isPodBackingOff here) — otherwise a controller
                        # touching annotations defeats exponential backoff
                        del tier[uid]
                        if entry.backoff_expiry > self._now():
                            self._backoff[uid] = entry
                            self._on_enqueue("backoff", EVENT_POD_UPDATE)
                        else:
                            self._active[uid] = entry
                            self._on_enqueue("active", EVENT_POD_UPDATE)
                    return
            if uid in self._in_flight:
                # being scheduled right now: refresh the in-flight object so
                # a requeue carries the new spec, but do NOT double-enqueue
                self._in_flight[uid].pod = pod
                return
            self.add(pod)

    def delete(self, pod_uid: str) -> None:
        with self._lock:
            for tier in (self._active, self._backoff, self._unschedulable):
                tier.pop(pod_uid, None)
            if pod_uid in self._in_flight:
                # mark so the cycle's requeue discards instead of
                # resurrecting a deleted pod
                self._deleted_in_flight.add(pod_uid)

    # ---- cycle boundary --------------------------------------------------

    def pop_ready(self) -> list[Pod]:
        """Drain the active tier — the whole next cycle's pending set.
        Flushes expired backoff first so a ready pod is never left behind."""
        with self._lock:
            self.flush_backoff()
            ready = [e.pod for e in self._active.values()]
            for e in self._active.values():
                e.attempts += 1
            self._in_flight = dict(self._active)
            self._deleted_in_flight.clear()
            self._active.clear()
            return ready

    def requeue_unschedulable(
        self, pod: Pod, reasons: Sequence[str] | str = ()
    ) -> None:
        """Cycle found no node (AddUnschedulableIfNotPresent). Goes to the
        unschedulable tier to wait for an event; backoff still advances so
        an event-triggered retry honors it. `reasons` names the rejecting
        plugins (drives the queueing-hint check on later events)."""
        if isinstance(reasons, str):
            reasons = (reasons,) if reasons else ()
        with self._lock:
            uid = pod.uid
            if uid in self._deleted_in_flight:
                self._deleted_in_flight.discard(uid)
                self._in_flight.pop(uid, None)
                return
            self._active.pop(uid, None)
            self._backoff.pop(uid, None)
            entry = self._in_flight.pop(uid, None) or _QueuedPod(pod)
            entry.pod = pod
            entry.unschedulable_reasons = tuple(reasons)
            entry.enqueued_at = self._now()
            entry.backoff_expiry = self._now() + self._backoff_for(entry.attempts)
            self._unschedulable[uid] = entry
            self._on_enqueue("unschedulable", "ScheduleAttemptFailure")

    def requeue_backoff(self, pod: Pod, event: str = "BindError") -> None:
        """Transient failure (e.g. bind error): retry after backoff."""
        with self._lock:
            uid = pod.uid
            if uid in self._deleted_in_flight:
                self._deleted_in_flight.discard(uid)
                self._in_flight.pop(uid, None)
                return
            self._active.pop(uid, None)
            self._unschedulable.pop(uid, None)
            entry = self._in_flight.pop(uid, None) or _QueuedPod(pod)
            entry.pod = pod
            entry.backoff_expiry = self._now() + self._backoff_for(entry.attempts)
            self._backoff[uid] = entry
            self._on_enqueue("backoff", event)

    def _backoff_for(self, attempts: int) -> float:
        return min(self._initial * (2 ** max(attempts - 1, 0)), self._max)

    # ---- event-driven movement ------------------------------------------

    def flush_backoff(self) -> int:
        with self._lock:
            now = self._now()
            expired = [
                u for u, e in self._backoff.items() if e.backoff_expiry <= now
            ]
            for u in expired:
                self._active[u] = self._backoff.pop(u)
                self._on_enqueue("active", "BackoffComplete")
            return len(expired)

    def flush_unschedulable_timeout(self) -> int:
        """Upstream flushUnschedulablePodsLeftover: pods stuck too long
        retry even without an event."""
        with self._lock:
            now = self._now()
            stuck = [
                u for u, e in self._unschedulable.items()
                if now - e.enqueued_at >= self._timeout
            ]
            for u in stuck:
                self._move_out(u, EVENT_UNSCHEDULABLE_TIMEOUT)
            return len(stuck)

    def move_all_to_active_or_backoff(self, event: str) -> int:
        """Informer event: move unschedulable pods whose failure the event
        can cure (queueing hints) to backoff (or active if expired)."""
        with self._lock:
            moved = 0
            for u in list(self._unschedulable):
                reasons = self._unschedulable[u].unschedulable_reasons
                if reasons and not any(
                    event in QUEUEING_HINTS.get(r, frozenset({event}))
                    for r in reasons
                ):
                    continue
                self._move_out(u, event)
                moved += 1
            return moved

    def _move_out(self, uid: str, event: str) -> None:
        entry = self._unschedulable.pop(uid, None)
        if entry is None:
            return
        if entry.backoff_expiry > self._now():
            self._backoff[uid] = entry
            self._on_enqueue("backoff", event)
        else:
            self._active[uid] = entry
            self._on_enqueue("active", event)

    # ---- introspection ---------------------------------------------------

    def attempts_of(self, uid: str) -> int:
        """Scheduling attempts the in-flight pod has used (1 = first try)."""
        with self._lock:
            e = self._in_flight.get(uid)
            return e.attempts if e else 1

    def pending_counts(self) -> dict[str, int]:
        """Tier sizes, keyed like the upstream pending_pods{queue=...}
        metric labels."""
        with self._lock:
            return {
                "active": len(self._active),
                "backoff": len(self._backoff),
                "unschedulable": len(self._unschedulable),
            }

    def all_pending(self) -> Iterable[Pod]:
        with self._lock:
            entries = [
                e.pod
                for tier in (self._active, self._backoff, self._unschedulable)
                for e in tier.values()
            ]
        return entries

    def __len__(self) -> int:
        with self._lock:
            return (
                len(self._active)
                + len(self._backoff)
                + len(self._unschedulable)
            )
