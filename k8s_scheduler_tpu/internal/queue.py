"""SchedulingQueue: active / backoff / unschedulable tiers.

The reference's `PriorityQueue` (`internal/queue/scheduling_queue.go` —
[UNVERIFIED], mount empty; SURVEY.md §2 C3) is a heap popped one pod at a
time by 16-way goroutine consumers. The TPU design schedules the WHOLE
ready set per cycle, so the heap collapses to set bookkeeping:

- `active`: pods ready for the next cycle. `pop_ready()` drains it (the
  batch analogue of Pop); ordering is re-derived by the encoder's
  `pod_order` (PrioritySort), so no heap is needed host-side.
- `backoff`: pods that failed recently, with an expiry deadline
  (exponential per-pod backoff, initial/max from config — upstream
  podInitialBackoffSeconds/podMaxBackoffSeconds). `flush_backoff()` moves
  expired entries back to active (upstream's flushBackoffQCompleted).
- `unschedulable`: pods that found no node and wait for a cluster event.
  `move_all_to_active_or_backoff(event)` relocates them (upstream
  MoveAllToActiveOrBackoffQueue on informer events), honoring the
  event→plugin queueing-hint table below.

Pods handed out by `pop_ready()` are tracked as in-flight until the cycle
requeues or drops them; a delete arriving mid-cycle marks the uid so the
requeue discards it instead of resurrecting a deleted pod. All public
methods take the queue lock — informer callbacks may run on other threads
than the scheduling loop (same discipline as SchedulerCache).

Time is injected (`now` callable) so tests drive the clock.

Durability contract (state/ package): every public mutator reads the
clock EXACTLY ONCE, applies its change through non-emitting internal
helpers, and emits EXACTLY ONE journal record carrying that clock value
— so replaying the record stream under a clock pinned to each record's
timestamp reproduces this queue bit-identically (attempt counts, backoff
expiries, tier membership, in-flight set). Internal helpers never emit
and never read the clock themselves.
"""

from __future__ import annotations

import dataclasses
import threading
import time as _time
from typing import Callable, Iterable, Sequence

from ..models.api import Pod
from .cache import _codec as _shared_codec

# Cluster events (the reference's framework.ClusterEvent resource/action
# pairs, collapsed to the ones that matter for requeueing).
EVENT_NODE_ADD = "NodeAdd"
EVENT_NODE_UPDATE = "NodeUpdate"
EVENT_NODE_DELETE = "NodeDelete"
EVENT_POD_ADD = "PodAdd"
EVENT_POD_UPDATE = "PodUpdate"
EVENT_POD_DELETE = "PodDelete"
EVENT_PVC_CHANGE = "PvcChange"  # PVC add/update (e.g. became bound)
EVENT_PV_CHANGE = "PvChange"  # PV add/update (e.g. became available)
EVENT_STORAGE_CLASS_CHANGE = "StorageClassChange"
EVENT_UNSCHEDULABLE_TIMEOUT = "UnschedulableTimeout"

# Which failure reasons (plugin names) an event can unstick — the
# queueing-hint registry (upstream EventsToRegister). A pod rejected by
# plugin X only requeues on events in HINTS[X]. Unknown reasons requeue on
# everything (conservative default, matches hintless upstream behavior).
QUEUEING_HINTS: dict[str, frozenset[str]] = {
    "NodeResourcesFit": frozenset(
        {EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_POD_DELETE}
    ),
    "NodeAffinity": frozenset({EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "NodeName": frozenset({EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "NodeUnschedulable": frozenset({EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "TaintToleration": frozenset({EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "NodePorts": frozenset({EVENT_NODE_ADD, EVENT_POD_DELETE}),
    "InterPodAffinity": frozenset(
        {EVENT_NODE_ADD, EVENT_POD_ADD, EVENT_POD_UPDATE, EVENT_POD_DELETE}
    ),
    "PodTopologySpread": frozenset(
        {EVENT_NODE_ADD, EVENT_POD_ADD, EVENT_POD_UPDATE, EVENT_POD_DELETE}
    ),
    "Coscheduling": frozenset({EVENT_POD_ADD, EVENT_POD_DELETE,
                               EVENT_NODE_ADD, EVENT_NODE_UPDATE}),
    "VolumeBinding": frozenset({
        EVENT_NODE_ADD, EVENT_NODE_UPDATE, EVENT_PVC_CHANGE,
        EVENT_PV_CHANGE, EVENT_STORAGE_CLASS_CHANGE,
    }),
}


def _codec_pod():
    """The journal's pod serializer, via the ONE lazy codec binding
    shared with SchedulerCache (cache._codec): bound on first use so
    schedulers without durability never import state/, and journaling
    mutators skip per-call import machinery inside the queue lock."""
    return _shared_codec()[0]


@dataclasses.dataclass
class _QueuedPod:
    pod: Pod
    attempts: int = 0  # scheduling attempts so far (drives backoff length)
    backoff_expiry: float = 0.0
    # plugins that rejected it (() = unknown -> requeue on any event). A pod
    # requeues when the event can cure ANY of its reasons (upstream: the
    # union of the failed plugins' EventsToRegister hints).
    unschedulable_reasons: tuple[str, ...] = ()
    enqueued_at: float = 0.0


class SchedulingQueue:
    def __init__(
        self,
        initial_backoff_seconds: float = 1.0,
        max_backoff_seconds: float = 10.0,
        unschedulable_timeout_seconds: float = 300.0,
        now: Callable[[], float] = _time.monotonic,
        on_enqueue: Callable[[str, str], None] | None = None,
        journal: Callable[[str, float, dict], None] | None = None,
    ) -> None:
        self._initial = initial_backoff_seconds
        self._max = max_backoff_seconds
        self._timeout = unschedulable_timeout_seconds
        self._now = now
        # (queue_name, event) observer for EVERY tier entry — feeds the
        # upstream scheduler_queue_incoming_pods_total metric; kept in the
        # queue so no transition undercounts
        self._on_enqueue = on_enqueue or (lambda queue, event: None)
        # (op, t, data) observer for the write-ahead journal (state/):
        # None = durability disabled. DurableState.attach wires it.
        self._journal = journal
        self._lock = threading.RLock()
        self._active: dict[str, _QueuedPod] = {}
        self._backoff: dict[str, _QueuedPod] = {}
        self._unschedulable: dict[str, _QueuedPod] = {}
        self._in_flight: dict[str, _QueuedPod] = {}
        self._deleted_in_flight: set[str] = set()

    def set_journal(
        self, journal: Callable[[str, float, dict], None] | None
    ) -> None:
        with self._lock:
            self._journal = journal

    def _emit(self, op: str, t: float, data: dict) -> None:
        if self._journal is not None:
            self._journal(op, t, data)

    # ---- intake ----------------------------------------------------------

    def add(self, pod: Pod) -> None:
        """New pod (informer Add): straight to active."""
        with self._lock:
            now = self._now()
            self._add_locked(pod, now, EVENT_POD_ADD)
            if self._journal is not None:
                self._emit("q.add", now, {"pod": _codec_pod()(pod)})

    def _add_locked(self, pod: Pod, now: float, event: str) -> None:
        uid = pod.uid
        self._backoff.pop(uid, None)
        self._unschedulable.pop(uid, None)
        self._active[uid] = _QueuedPod(pod, enqueued_at=now)
        self._on_enqueue("active", event)

    def update(self, pod: Pod) -> None:
        """Spec/labels changed: an update can unstick its own pod."""
        with self._lock:
            now = self._now()
            if self._journal is not None:
                self._emit("q.update", now, {"pod": _codec_pod()(pod)})
            uid = pod.uid
            for tier in (self._active, self._backoff, self._unschedulable):
                if uid in tier:
                    entry = tier[uid]
                    entry.pod = pod
                    if tier is self._unschedulable:
                        # the update may cure the failure, but the pod's
                        # backoff window still applies (upstream checks
                        # isPodBackingOff here) — otherwise a controller
                        # touching annotations defeats exponential backoff
                        del tier[uid]
                        if entry.backoff_expiry > now:
                            self._backoff[uid] = entry
                            self._on_enqueue("backoff", EVENT_POD_UPDATE)
                        else:
                            self._active[uid] = entry
                            self._on_enqueue("active", EVENT_POD_UPDATE)
                    return
            if uid in self._in_flight:
                # being scheduled right now: refresh the in-flight object so
                # a requeue carries the new spec, but do NOT double-enqueue
                self._in_flight[uid].pod = pod
                return
            self._add_locked(pod, now, EVENT_POD_ADD)

    def delete(self, pod_uid: str) -> None:
        with self._lock:
            changed = False
            for tier in (self._active, self._backoff, self._unschedulable):
                if tier.pop(pod_uid, None) is not None:
                    changed = True
            if pod_uid in self._in_flight:
                # mark so the cycle's requeue discards instead of
                # resurrecting a deleted pod
                self._deleted_in_flight.add(pod_uid)
                changed = True
            if changed:  # a no-op delete journals nothing (replay-exact)
                self._emit("q.delete", self._now(), {"uid": pod_uid})

    # ---- cycle boundary --------------------------------------------------

    def pop_ready(self, hold: bool = False) -> list[Pod]:
        """Drain the active tier — the whole next cycle's pending set.
        Flushes expired backoff first so a ready pod is never left behind.

        `hold=True` is the multi-cycle coalescing variant: groups popped
        by EARLIER cycles are still buffered scheduler-side (their
        outcomes apply at the batch flush), so this pop ACCUMULATES into
        the in-flight set instead of replacing it, and keeps the
        deleted-in-flight tombstones — otherwise a buffered pod would
        lose its attempts count, its delete tombstone, and its crash
        recovery (recover_in_flight) the moment the next group was
        popped. The flag is journaled: replay must reproduce the exact
        in-flight set a takeover recovers."""
        with self._lock:
            now = self._now()
            # journal only a pop that changes SOMETHING: drains pods,
            # flushes backoff, or retires a previous in-flight set — an
            # idle scheduler's empty cycles must not grow the journal
            had_inflight = not hold and (
                bool(self._in_flight) or bool(self._deleted_in_flight)
            )
            flushed = self._flush_backoff_locked(now, "BackoffComplete")
            ready = [e.pod for e in self._active.values()]
            for e in self._active.values():
                e.attempts += 1
            if hold:
                self._in_flight.update(self._active)
            else:
                self._in_flight = dict(self._active)
                self._deleted_in_flight.clear()
            self._active.clear()
            if ready or flushed or had_inflight:
                self._emit(
                    "q.pop", now, {"hold": True} if hold else {}
                )
            return ready

    def retire_in_flight(self, uids: Sequence[str]) -> None:
        """A multi-cycle batch flush applied these pods' outcomes: drop
        them (and their delete tombstones) from the in-flight set.

        Single-cycle serving retires implicitly — the next non-hold
        pop replaces the whole set — but hold pops only ever
        ACCUMULATE, and out-of-phase profile buffers can keep every
        pop holding, so without an explicit retire a bound pod would
        stay "recoverable" forever: unbounded in-flight growth, and a
        leader takeover re-scheduling (re-binding) pods bound
        arbitrarily long ago. Pods the failure paths already requeued
        are not in the set — the membership filter skips them."""
        with self._lock:
            live = [
                u for u in uids
                if u in self._in_flight or u in self._deleted_in_flight
            ]
            if not live:
                return
            now = self._now()
            self._emit("q.retire", now, {"uids": live})
            for u in live:
                self._in_flight.pop(u, None)
                self._deleted_in_flight.discard(u)

    def requeue_unschedulable(
        self, pod: Pod, reasons: Sequence[str] | str = ()
    ) -> None:
        """Cycle found no node (AddUnschedulableIfNotPresent). Goes to the
        unschedulable tier to wait for an event; backoff still advances so
        an event-triggered retry honors it. `reasons` names the rejecting
        plugins (drives the queueing-hint check on later events)."""
        if isinstance(reasons, str):
            reasons = (reasons,) if reasons else ()
        with self._lock:
            now = self._now()
            uid = pod.uid
            # journal BEFORE the deleted-in-flight check: the discard
            # branch mutates state too (clears the tombstone + in-flight
            # entry), and replay must take the same branch it took live
            if self._journal is not None:
                self._emit(
                    "q.unsched", now,
                    {"pod": _codec_pod()(pod),
                     "reasons": list(reasons)},
                )
            if uid in self._deleted_in_flight:
                self._deleted_in_flight.discard(uid)
                self._in_flight.pop(uid, None)
                return
            self._active.pop(uid, None)
            self._backoff.pop(uid, None)
            entry = self._in_flight.pop(uid, None) or _QueuedPod(pod)
            entry.pod = pod
            entry.unschedulable_reasons = tuple(reasons)
            entry.enqueued_at = now
            entry.backoff_expiry = now + self._backoff_for(entry.attempts)
            self._unschedulable[uid] = entry
            self._on_enqueue("unschedulable", "ScheduleAttemptFailure")

    def requeue_backoff(self, pod: Pod, event: str = "BindError") -> None:
        """Transient failure (e.g. bind error): retry after backoff."""
        with self._lock:
            now = self._now()
            uid = pod.uid
            # journal before the deleted-in-flight check (see
            # requeue_unschedulable: the discard branch mutates state)
            if self._journal is not None:
                self._emit(
                    "q.backoff", now,
                    {"pod": _codec_pod()(pod), "event": event},
                )
            if uid in self._deleted_in_flight:
                self._deleted_in_flight.discard(uid)
                self._in_flight.pop(uid, None)
                return
            self._active.pop(uid, None)
            self._unschedulable.pop(uid, None)
            entry = self._in_flight.pop(uid, None) or _QueuedPod(pod)
            entry.pod = pod
            entry.backoff_expiry = now + self._backoff_for(entry.attempts)
            self._backoff[uid] = entry
            self._on_enqueue("backoff", event)

    def _backoff_for(self, attempts: int) -> float:
        return min(self._initial * (2 ** max(attempts - 1, 0)), self._max)

    # ---- event-driven movement ------------------------------------------

    def flush_backoff(self) -> int:
        with self._lock:
            now = self._now()
            n = self._flush_backoff_locked(now, "BackoffComplete")
            if n:  # no-op flushes journal nothing
                self._emit("q.flush_backoff", now, {})
            return n

    def _flush_backoff_locked(self, now: float, event: str) -> int:
        expired = [
            u for u, e in self._backoff.items() if e.backoff_expiry <= now
        ]
        for u in expired:
            self._active[u] = self._backoff.pop(u)
            self._on_enqueue("active", event)
        return len(expired)

    def flush_unschedulable_timeout(self) -> int:
        """Upstream flushUnschedulablePodsLeftover: pods stuck too long
        retry even without an event."""
        with self._lock:
            now = self._now()
            stuck = [
                u for u, e in self._unschedulable.items()
                if now - e.enqueued_at >= self._timeout
            ]
            for u in stuck:
                self._move_out(u, EVENT_UNSCHEDULABLE_TIMEOUT, now)
            if stuck:  # no-op sweeps journal nothing
                self._emit("q.flush_timeout", now, {})
            return len(stuck)

    def move_all_to_active_or_backoff(self, event: str) -> int:
        """Informer event: move unschedulable pods whose failure the event
        can cure (queueing hints) to backoff (or active if expired)."""
        with self._lock:
            now = self._now()
            moved = 0
            for u in list(self._unschedulable):
                reasons = self._unschedulable[u].unschedulable_reasons
                if reasons and not any(
                    event in QUEUEING_HINTS.get(r, frozenset({event}))
                    for r in reasons
                ):
                    continue
                self._move_out(u, event, now)
                moved += 1
            if moved:
                # gated: this runs on EVERY informer event — journaling
                # the no-op moves would dominate the journal at scale
                self._emit("q.move", now, {"event": event})
            return moved

    def _move_out(self, uid: str, event: str, now: float) -> None:
        entry = self._unschedulable.pop(uid, None)
        if entry is None:
            return
        if entry.backoff_expiry > now:
            self._backoff[uid] = entry
            self._on_enqueue("backoff", event)
        else:
            self._active[uid] = entry
            self._on_enqueue("active", event)

    # ---- durability (state/ package) -------------------------------------

    def recover_in_flight(self) -> int:
        """Takeover recovery: requeue pods that were IN FLIGHT when the
        previous leader died — their cycle's outcome records never made
        it to the journal, so without this they would be silently
        dropped by the next pop_ready's in-flight reset. Attempts are
        preserved (the crashed attempt never concluded); a pod the
        informer re-added meanwhile keeps its fresher active entry.
        Journaled like any mutator, so a crash right after recovery
        replays it. The Scheduler calls this once after
        DurableState.attach; replay applies it via the q.recover op."""
        with self._lock:
            now = self._now()
            n = 0
            for uid, e in self._in_flight.items():
                if uid in self._deleted_in_flight:
                    continue
                if uid not in self._active:
                    e.enqueued_at = now
                    self._active[uid] = e
                    self._on_enqueue("active", "LeaderTakeover")
                    n += 1
            had = bool(self._in_flight) or bool(self._deleted_in_flight)
            self._in_flight = {}
            self._deleted_in_flight.clear()
            if had:
                self._emit("q.recover", now, {})
            return n

    def dump_state(self) -> dict:
        """Full durable state as JSON-able plain data (snapshot payload).
        Tier entry order is insertion order and is part of the contract —
        replay reproduces it, so digests compare order-sensitively."""
        from ..state.codec import pod_to_state

        def entry(e: _QueuedPod) -> dict:
            return {
                "pod": pod_to_state(e.pod),
                "attempts": e.attempts,
                "backoff_expiry": e.backoff_expiry,
                "reasons": list(e.unschedulable_reasons),
                "enqueued_at": e.enqueued_at,
            }

        with self._lock:
            return {
                "active": [entry(e) for e in self._active.values()],
                "backoff": [entry(e) for e in self._backoff.values()],
                "unschedulable": [
                    entry(e) for e in self._unschedulable.values()
                ],
                "in_flight": [entry(e) for e in self._in_flight.values()],
                "deleted_in_flight": sorted(self._deleted_in_flight),
            }

    def load_state(self, state: dict) -> None:
        """Inverse of dump_state: replace this queue's contents. Expiry
        and enqueue timestamps are restored verbatim — they are
        CLOCK_MONOTONIC values valid on the host that wrote them (the
        same-host failover contract; see state/__init__)."""
        from ..state.codec import pod_from_state

        def entry(d: dict) -> _QueuedPod:
            return _QueuedPod(
                pod=pod_from_state(d["pod"]),
                attempts=int(d.get("attempts", 0)),
                backoff_expiry=float(d.get("backoff_expiry", 0.0)),
                unschedulable_reasons=tuple(d.get("reasons", ())),
                enqueued_at=float(d.get("enqueued_at", 0.0)),
            )

        with self._lock:
            for name, tier in (
                ("active", self._active),
                ("backoff", self._backoff),
                ("unschedulable", self._unschedulable),
                ("in_flight", self._in_flight),
            ):
                tier.clear()
                for d in state.get(name, ()):
                    e = entry(d)
                    tier[e.pod.uid] = e
            self._deleted_in_flight = set(state.get("deleted_in_flight", ()))

    # ---- introspection ---------------------------------------------------

    def attempts_of(self, uid: str) -> int:
        """Scheduling attempts the in-flight pod has used (1 = first try)."""
        with self._lock:
            e = self._in_flight.get(uid)
            return e.attempts if e else 1

    def pending_counts(self) -> dict[str, int]:
        """Tier sizes, keyed like the upstream pending_pods{queue=...}
        metric labels."""
        with self._lock:
            return {
                "active": len(self._active),
                "backoff": len(self._backoff),
                "unschedulable": len(self._unschedulable),
            }

    def all_pending(self) -> Iterable[Pod]:
        with self._lock:
            entries = [
                e.pod
                for tier in (self._active, self._backoff, self._unschedulable)
                for e in tier.values()
            ]
        return entries

    def __len__(self) -> int:
        with self._lock:
            return (
                len(self._active)
                + len(self._backoff)
                + len(self._unschedulable)
            )
