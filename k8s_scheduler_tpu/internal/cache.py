"""SchedulerCache: node/pod stores with assume/confirm/forget lifecycle.

The reference's cache (`internal/cache/cache.go` — [UNVERIFIED], mount
empty; SURVEY.md §2 C4) keeps a per-node `NodeInfo` aggregate mutated by
informer events, plus "assumed" pods: optimistically placed by the
scheduling cycle before the API bind confirms, expiring on a TTL if the
confirmation never lands. This port keeps the same lifecycle but the
aggregation itself lives in the snapshot encoder (structure-of-arrays
tensors); the cache's job is to own the object lists the encoder consumes
and to answer "which pods count as existing on node X right now".

Lifecycle (mirrors upstream):
    assume(pod, node)      cycle picked a node; counts as existing at once
    finish_binding(pod)    bind RPC dispatched; TTL starts
    confirm(pod)           API bound event arrived; assumed -> bound
    forget(pod)            bind failed; drop the assumption
    cleanup_expired()      assumed-pod TTL sweep (upstream cleanupAssumedPods)

Time is injected for tests. Thread-safety: a single lock around mutations —
the cycle runs single-threaded; informer callbacks may come from elsewhere.
"""

from __future__ import annotations

import dataclasses
import threading
import time as _time
from typing import Callable

from ..models.api import Node, Pod


@dataclasses.dataclass
class _AssumedPod:
    pod: Pod
    node_name: str
    binding_finished: bool = False
    deadline: float = 0.0


class SchedulerCache:
    def __init__(
        self,
        assumed_pod_ttl_seconds: float = 30.0,
        now: Callable[[], float] = _time.monotonic,
    ) -> None:
        self._ttl = assumed_pod_ttl_seconds
        self._now = now
        self._lock = threading.Lock()
        self._nodes: dict[str, Node] = {}
        self._bound: dict[str, tuple[Pod, str]] = {}  # uid -> (pod, node)
        self._assumed: dict[str, _AssumedPod] = {}

    # ---- node events -----------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node

    def update_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node

    def remove_node(self, node_name: str) -> None:
        with self._lock:
            self._nodes.pop(node_name, None)

    # ---- pod events (bound pods observed via informer) -------------------

    def add_pod(self, pod: Pod, node_name: str) -> None:
        """A bound pod appeared (or an assumed pod's bind was observed)."""
        with self._lock:
            self._assumed.pop(pod.uid, None)
            self._bound[pod.uid] = (pod, node_name)

    def remove_pod(self, pod_uid: str) -> None:
        with self._lock:
            self._bound.pop(pod_uid, None)
            self._assumed.pop(pod_uid, None)

    # ---- assume lifecycle ------------------------------------------------

    def assume(self, pod: Pod, node_name: str) -> None:
        with self._lock:
            if pod.uid in self._bound:
                raise ValueError(f"pod {pod.name} already bound")
            self._assumed[pod.uid] = _AssumedPod(pod, node_name)

    def finish_binding(self, pod_uid: str) -> None:
        with self._lock:
            a = self._assumed.get(pod_uid)
            if a is not None:
                a.binding_finished = True
                a.deadline = self._now() + self._ttl

    def confirm(self, pod_uid: str) -> None:
        """Bind confirmed by the cluster store (add_pod also confirms)."""
        with self._lock:
            a = self._assumed.pop(pod_uid, None)
            if a is not None:
                self._bound[pod_uid] = (a.pod, a.node_name)

    def forget(self, pod_uid: str) -> None:
        with self._lock:
            self._assumed.pop(pod_uid, None)

    def is_assumed(self, pod_uid: str) -> bool:
        with self._lock:
            return pod_uid in self._assumed

    def cleanup_expired(self) -> list[Pod]:
        """Drop assumed pods whose bind confirmation never arrived; returns
        them so the caller can requeue (upstream logs and drops — the
        informer re-delivers the pod as still-pending)."""
        now = self._now()
        with self._lock:
            gone = [
                u for u, a in self._assumed.items()
                if a.binding_finished and a.deadline <= now
            ]
            return [self._assumed.pop(u).pod for u in gone]

    # ---- snapshot --------------------------------------------------------

    def nodes(self) -> list[Node]:
        with self._lock:
            return list(self._nodes.values())

    def existing_pods(self) -> list[tuple[Pod, str]]:
        """Bound + assumed pods — what the encoder treats as `existing`."""
        with self._lock:
            out = list(self._bound.values())
            out.extend((a.pod, a.node_name) for a in self._assumed.values())
            return out

    def counts(self) -> dict[str, int]:
        with self._lock:
            return {
                "nodes": len(self._nodes),
                "bound": len(self._bound),
                "assumed": len(self._assumed),
            }
