"""SchedulerCache: node/pod stores with assume/confirm/forget lifecycle.

The reference's cache (`internal/cache/cache.go` — [UNVERIFIED], mount
empty; SURVEY.md §2 C4) keeps a per-node `NodeInfo` aggregate mutated by
informer events, plus "assumed" pods: optimistically placed by the
scheduling cycle before the API bind confirms, expiring on a TTL if the
confirmation never lands. This port keeps the same lifecycle but the
aggregation itself lives in the snapshot encoder (structure-of-arrays
tensors); the cache's job is to own the object lists the encoder consumes
and to answer "which pods count as existing on node X right now".

Lifecycle (mirrors upstream):
    assume(pod, node)      cycle picked a node; counts as existing at once
    finish_binding(pod)    bind RPC dispatched; TTL starts
    confirm(pod)           API bound event arrived; assumed -> bound
    forget(pod)            bind failed; drop the assumption
    cleanup_expired()      assumed-pod TTL sweep (upstream cleanupAssumedPods)

Time is injected for tests. Thread-safety: a single RLock around
mutations — the cycle runs single-threaded; informer callbacks may come
from elsewhere (re-entrant so the durable-state snapshot can hold it
across a consistent dump).

Durability contract (state/ package): same as SchedulingQueue — each
public mutator reads the clock once, applies, and emits one journal
record with that clock value, so replay under a pinned clock reproduces
assumed-pod TTL deadlines exactly.
"""

from __future__ import annotations

import dataclasses
import threading
import time as _time
from typing import Callable

from ..models.api import Node, Pod

# codec bindings for journal emission, bound on first use so schedulers
# without durability never import state/ — and journaling mutators skip
# per-call import machinery inside the cache lock
_pod_to_state = _node_to_state = None


def _codec():
    global _pod_to_state, _node_to_state
    if _pod_to_state is None:
        from ..state.codec import node_to_state, pod_to_state

        _pod_to_state = pod_to_state
        _node_to_state = node_to_state
    return _pod_to_state, _node_to_state


@dataclasses.dataclass
class _AssumedPod:
    pod: Pod
    node_name: str
    binding_finished: bool = False
    deadline: float = 0.0


class SchedulerCache:
    def __init__(
        self,
        assumed_pod_ttl_seconds: float = 30.0,
        now: Callable[[], float] = _time.monotonic,
        journal: Callable[[str, float, dict], None] | None = None,
    ) -> None:
        self._ttl = assumed_pod_ttl_seconds
        self._now = now
        self._lock = threading.RLock()
        self._journal = journal
        self._nodes: dict[str, Node] = {}
        self._bound: dict[str, tuple[Pod, str]] = {}  # uid -> (pod, node)
        self._assumed: dict[str, _AssumedPod] = {}

    def set_journal(
        self, journal: Callable[[str, float, dict], None] | None
    ) -> None:
        with self._lock:
            self._journal = journal

    def _emit(self, op: str, data: dict) -> None:
        if self._journal is not None:
            self._journal(op, self._now(), data)

    def _emit_node(self, op: str, node: Node) -> None:
        if self._journal is not None:
            self._journal(
                op, self._now(), {"node": _codec()[1](node)}
            )

    # ---- node events -----------------------------------------------------

    def add_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node
            self._emit_node("c.add_node", node)

    def update_node(self, node: Node) -> None:
        with self._lock:
            self._nodes[node.name] = node
            self._emit_node("c.update_node", node)

    def remove_node(self, node_name: str) -> None:
        with self._lock:
            if self._nodes.pop(node_name, None) is not None:
                self._emit("c.remove_node", {"name": node_name})

    # ---- pod events (bound pods observed via informer) -------------------

    def add_pod(self, pod: Pod, node_name: str) -> None:
        """A bound pod appeared (or an assumed pod's bind was observed)."""
        with self._lock:
            self._assumed.pop(pod.uid, None)
            self._bound[pod.uid] = (pod, node_name)
            if self._journal is not None:
                self._emit(
                    "c.add_pod",
                    {"pod": _codec()[0](pod), "node": node_name},
                )

    def remove_pod(self, pod_uid: str) -> None:
        with self._lock:
            b = self._bound.pop(pod_uid, None)
            a = self._assumed.pop(pod_uid, None)
            if b is not None or a is not None:
                self._emit("c.remove_pod", {"uid": pod_uid})

    # ---- assume lifecycle ------------------------------------------------

    def assume(self, pod: Pod, node_name: str) -> None:
        with self._lock:
            if pod.uid in self._bound:
                # raise WITHOUT emitting: a refused assume must not be
                # replayed (replay would refuse it again and abort)
                raise ValueError(f"pod {pod.name} already bound")
            self._assumed[pod.uid] = _AssumedPod(pod, node_name)
            if self._journal is not None:
                self._emit(
                    "c.assume",
                    {"pod": _codec()[0](pod), "node": node_name},
                )

    def finish_binding(self, pod_uid: str) -> None:
        with self._lock:
            now = self._now()
            a = self._assumed.get(pod_uid)
            if a is not None:
                a.binding_finished = True
                a.deadline = now + self._ttl
                if self._journal is not None:
                    self._journal("c.finish_binding", now, {"uid": pod_uid})

    def confirm(self, pod_uid: str) -> None:
        """Bind confirmed by the cluster store (add_pod also confirms)."""
        with self._lock:
            a = self._assumed.pop(pod_uid, None)
            if a is not None:
                self._bound[pod_uid] = (a.pod, a.node_name)
                self._emit("c.confirm", {"uid": pod_uid})

    def forget(self, pod_uid: str) -> None:
        with self._lock:
            if self._assumed.pop(pod_uid, None) is not None:
                self._emit("c.forget", {"uid": pod_uid})

    def is_assumed(self, pod_uid: str) -> bool:
        with self._lock:
            return pod_uid in self._assumed

    def has_pod(self, pod_uid: str) -> bool:
        """Known to the cluster state: bound or assumed."""
        with self._lock:
            return pod_uid in self._bound or pod_uid in self._assumed

    def cleanup_expired(self) -> list[tuple[Pod, str]]:
        """Drop assumed pods whose bind confirmation never arrived;
        returns (pod, node_name) pairs so the caller can requeue AND
        explain the expiry (events ring + pod timeline — upstream logs
        and drops; the informer re-delivers the pod as still-pending)."""
        with self._lock:
            now = self._now()
            gone = [
                u for u, a in self._assumed.items()
                if a.binding_finished and a.deadline <= now
            ]
            out = []
            for u in gone:
                a = self._assumed.pop(u)
                out.append((a.pod, a.node_name))
            if out and self._journal is not None:
                # gated: this sweep runs every cycle — an idle scheduler
                # must not grow the journal with no-op records. Emits the
                # SAME `now` the sweep used (read-clock-once contract): a
                # second read could stamp a later t under which replay
                # would expire deadlines this sweep did not.
                self._journal("c.expire", now, {})
            return out

    # ---- durability (state/ package) -------------------------------------

    def dump_state(self) -> dict:
        from ..state.codec import node_to_state, pod_to_state

        with self._lock:
            return {
                "nodes": [
                    node_to_state(n) for n in self._nodes.values()
                ],
                "bound": [
                    {"pod": pod_to_state(p), "node": n}
                    for p, n in self._bound.values()
                ],
                "assumed": [
                    {
                        "pod": pod_to_state(a.pod),
                        "node": a.node_name,
                        "finished": a.binding_finished,
                        "deadline": a.deadline,
                    }
                    for a in self._assumed.values()
                ],
            }

    def load_state(self, state: dict) -> None:
        from ..state.codec import node_from_state, pod_from_state

        with self._lock:
            self._nodes.clear()
            self._bound.clear()
            self._assumed.clear()
            for d in state.get("nodes", ()):
                n = node_from_state(d)
                self._nodes[n.name] = n
            for d in state.get("bound", ()):
                p = pod_from_state(d["pod"])
                self._bound[p.uid] = (p, d["node"])
            for d in state.get("assumed", ()):
                p = pod_from_state(d["pod"])
                self._assumed[p.uid] = _AssumedPod(
                    pod=p,
                    node_name=d["node"],
                    binding_finished=bool(d.get("finished", False)),
                    deadline=float(d.get("deadline", 0.0)),
                )

    # ---- snapshot --------------------------------------------------------

    def nodes(self) -> list[Node]:
        with self._lock:
            return list(self._nodes.values())

    def existing_pods(self) -> list[tuple[Pod, str]]:
        """Bound + assumed pods — what the encoder treats as `existing`."""
        with self._lock:
            out = list(self._bound.values())
            out.extend((a.pod, a.node_name) for a in self._assumed.values())
            return out

    def counts(self) -> dict[str, int]:
        with self._lock:
            return {
                "nodes": len(self._nodes),
                "bound": len(self._bound),
                "assumed": len(self._assumed),
            }
