from .cache import SchedulerCache  # noqa: F401
from .queue import SchedulingQueue  # noqa: F401
