"""THREADS (TR0xx, lifecycle half): thread-role inference + TR003.

Since PR 3 the scheduler is a multi-threaded host program: the journal
writer, the decision-fetch watchdog worker, the compile warmer, the
lease renewer, and the HTTP metrics/debug server all run concurrently
with the serve loop, plus observer hooks and scrape-time gauge closures
that execute on whichever thread publishes or scrapes. Their safety
rests on convention; this module turns the conventions into a machine-
checked role model shared by the RACES pass (analysis/races.py).

Role inference (`thread_roles`), all structural — no imports of the
analyzed code:

- every `threading.Thread(target=f, name="...")` creation site seeds a
  role (named by the thread-name literal when given, else the target's
  name) rooted at the resolved target — `Thread(target=...)` first-args
  count as called (analysis/callgraph.py), and the role set rides the
  same resolution;
- methods of `BaseHTTPRequestHandler` subclasses seed the `httpserver`
  role (the stdlib server invokes them on its own threads, so the
  Thread-target walk alone cannot reach them);
- callables registered via `<x>.observers.append(f)` seed `observer`
  (FlightRecorder publish-time hooks);
- callables registered via `.set_function(f)` seed `scrape` (gauges
  evaluated on the scraping thread, i.e. under the HTTP server);
- functions named `schedule_cycle`, or a method named `Cycle`, seed
  `serve` — the serve-loop entry points (the gRPC Cycle RPC drives
  Scheduler.schedule_cycle).

Roles propagate interprocedurally over the shared call graph; a
function reachable from two roles carries both (that is the point —
it is the code two threads can execute concurrently).

TR003 (this pass): a spawned thread must have a join / drain-exit /
lazy-respawn story — the CompileWarmer leak class, caught by hand in
PR 7 review. A `threading.Thread(...)` whose object is (a) dropped on
the floor, or (b) stored but never `.join()`ed anywhere in its module
and never cleared (`<attr> = None` — the drain-exit/abandon pattern of
CompileWarmer._run and _FetchWorker.run) is flagged at the creation
site. `daemon=True` alone is NOT a story: a daemon HTTP thread still
holds its socket until process exit (the cmd/httpserver.py instance
this rule was written against).
"""

from __future__ import annotations

import ast
import dataclasses

from .callgraph import CodeIndex, FuncInfo, attribute_chain, own_body_nodes
from .core import Finding, LintContext
from .registry import PassBase

# serve-loop entry points (see module docstring): the gRPC Cycle RPC
# and the Scheduler cycle driver it serializes
SERVE_ENTRY_FUNCTIONS = frozenset({"schedule_cycle"})
SERVE_ENTRY_METHODS = frozenset({"Cycle"})


@dataclasses.dataclass
class ThreadSite:
    """One `threading.Thread(...)` creation site."""

    file: str  # repo-relative
    lineno: int
    role: str  # thread-name literal or target name
    target_ids: frozenset[str]  # resolved target function ids
    daemon: bool
    # where the Thread object went: ("attr", name) for self.X = Thread,
    # ("name", name) for x = Thread, or None when dropped
    stored: tuple[str, str] | None
    creator: str  # qualname of the creating function ("<module>" at top)


def _thread_call(node: ast.AST) -> ast.Call | None:
    """The Call node when `node` constructs a threading.Thread."""
    if not isinstance(node, ast.Call):
        return None
    chain = attribute_chain(node.func)
    if chain and chain[-1] == "Thread":
        return node
    return None


def _kwarg(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _module_shim(sf) -> FuncInfo:
    return FuncInfo(
        id=f"{sf.rel}::<module>", file=sf, node=sf.tree,
        name="<module>", qualname="<module>", cls=None,
        parent=None, lineno=1,
    )


def _frames(index: CodeIndex):
    """Every (FuncInfo, own-body nodes) frame, functions then modules."""
    for f in index.funcs.values():
        yield f, own_body_nodes(f.node)
    for sf in index.files:
        yield _module_shim(sf), own_body_nodes(sf.tree)


def find_thread_sites(ctx: LintContext) -> list[ThreadSite]:
    index = ctx.index

    def _storage(t: ast.AST) -> tuple[str, str] | None:
        if isinstance(t, ast.Attribute):
            return ("attr", t.attr)
        if isinstance(t, ast.Name):
            return ("name", t.id)
        return None

    sites: list[ThreadSite] = []
    seen_calls: set[tuple[str, int, int]] = set()
    stored_at: dict[tuple[str, int, int], tuple[str, str]] = {}
    for f, nodes in _frames(index):
        for node in nodes:
            # storage shapes: <target> [= <target2>] = Thread(...), and
            # the elementwise  a, b = Thread(...), Thread(...)  unpack
            if isinstance(node, ast.Assign):
                calls_and_targets: list = []
                call = _thread_call(node.value)
                if call is not None:
                    for t in node.targets:
                        calls_and_targets.append((call, t))
                elif (
                    isinstance(node.value, ast.Tuple)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Tuple)
                    and len(node.targets[0].elts)
                    == len(node.value.elts)
                ):
                    calls_and_targets = [
                        (_thread_call(v), t)
                        for v, t in zip(
                            node.value.elts, node.targets[0].elts
                        )
                    ]
                for call, t in calls_and_targets:
                    if call is None:
                        continue
                    key = (f.file.rel, call.lineno, call.col_offset)
                    st = _storage(t)
                    if st is not None and key not in stored_at:
                        stored_at[key] = st
            call = _thread_call(node)
            if call is None:
                continue
            key = (f.file.rel, call.lineno, call.col_offset)
            if key in seen_calls:
                continue
            seen_calls.add(key)
            stored = stored_at.get(key)
            target = _kwarg(call, "target")
            name_v = _kwarg(call, "name")
            daemon_v = _kwarg(call, "daemon")
            targets = index.resolve_callback(f, target)
            role = None
            if isinstance(name_v, ast.Constant) and isinstance(
                name_v.value, str
            ):
                role = name_v.value
            elif target is not None:
                tchain = attribute_chain(target)
                if tchain:
                    role = tchain[-1]
            if role is None:
                role = f"thread@{f.file.rel}:{call.lineno}"
            daemon = bool(
                isinstance(daemon_v, ast.Constant) and daemon_v.value
            )
            sites.append(ThreadSite(
                file=f.file.rel, lineno=call.lineno, role=role,
                target_ids=frozenset(targets), daemon=daemon,
                stored=stored, creator=f.qualname,
            ))
    sites.sort(key=lambda s: (s.file, s.lineno))
    return sites


def _registration_roots(ctx: LintContext) -> dict[str, set[str]]:
    """observer / scrape / httpserver / serve role roots."""
    index = ctx.index
    roots: dict[str, set[str]] = {
        "observer": set(), "scrape": set(),
        "httpserver": set(), "serve": set(),
    }
    for f, nodes in _frames(index):
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            if (
                fn.attr == "append"
                and isinstance(fn.value, ast.Attribute)
                and fn.value.attr == "observers"
                and node.args
            ):
                roots["observer"] |= index.resolve_callback(
                    f, node.args[0]
                )
            elif fn.attr == "set_function" and node.args:
                roots["scrape"] |= index.resolve_callback(
                    f, node.args[0]
                )
    for ci in index.subclasses_of("BaseHTTPRequestHandler"):
        roots["httpserver"].update(ci.methods.values())
    for fid, f in index.funcs.items():
        if f.name in SERVE_ENTRY_FUNCTIONS:
            roots["serve"].add(fid)
        elif f.cls is not None and f.name in SERVE_ENTRY_METHODS:
            roots["serve"].add(fid)
    return roots


def thread_roles(
    ctx: LintContext,
) -> tuple[list[ThreadSite], dict[str, frozenset[str]]]:
    """(thread creation sites, function id -> role set), memoized on the
    context so THREADS and RACES share one computation."""
    cached = getattr(ctx, "_thread_roles", None)
    if cached is not None:
        return cached
    index = ctx.index
    sites = find_thread_sites(ctx)
    roots: dict[str, set[str]] = {}
    for s in sites:
        if s.target_ids:
            roots.setdefault(s.role, set()).update(s.target_ids)
    for role, ids in _registration_roots(ctx).items():
        if ids:
            roots.setdefault(role, set()).update(ids)
    role_of: dict[str, set[str]] = {}
    for role, ids in roots.items():
        for fid in index.reachable(ids):
            role_of.setdefault(fid, set()).add(role)
    frozen = {fid: frozenset(rs) for fid, rs in role_of.items()}
    ctx._thread_roles = (sites, frozen)
    return ctx._thread_roles


class ThreadsPass(PassBase):
    name = "THREADS"
    codes = {
        "TR003": "spawned thread has no join / drain-exit / respawn "
                 "story (the CompileWarmer leak class)",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        sites, _roles = thread_roles(ctx)
        findings: list[Finding] = []
        for s in sites:
            sf = ctx.file(s.file)
            if sf is None:
                continue
            if s.stored is None:
                findings.append(Finding(
                    s.file, s.lineno, "TR003",
                    f"{s.creator} spawns thread {s.role!r} and drops "
                    "the Thread object: nothing can ever join or drain "
                    "it — store it and join on shutdown (see "
                    "CompileWarmer's drain-exit for the lazy-respawn "
                    "alternative)",
                ))
                continue
            kind, name = s.stored
            if self._has_lifecycle(sf, kind, name, s):
                continue
            findings.append(Finding(
                s.file, s.lineno, "TR003",
                f"{s.creator} spawns thread {s.role!r} into "
                f"{'.' + name if kind == 'attr' else name} but the "
                "module never joins it and never clears the reference "
                "(the drain-exit/abandon pattern): the thread leaks "
                "past shutdown"
                + (" — daemon=True only hides the leak until process "
                   "exit" if s.daemon else ""),
            ))
        return findings

    @staticmethod
    def _has_lifecycle(sf, kind: str, name: str, site: ThreadSite) -> bool:
        """A join (`<...>.name.join(...)` / `name.join(...)`) or a
        reference clear (`<...>.name = None` / `name = None`) anywhere
        in the module counts as the lifecycle story. Module-scoped on
        purpose: shutdown joins usually live in a different method than
        the spawn (Journal.close vs Journal.append)."""
        for node in sf.walk():
            if isinstance(node, ast.Call):
                chain = attribute_chain(node.func)
                if chain and len(chain) >= 2 and chain[-1] == "join" \
                        and chain[-2] == name:
                    return True
            elif isinstance(node, ast.Assign):
                if not (
                    isinstance(node.value, ast.Constant)
                    and node.value.value is None
                ):
                    continue
                for t in node.targets:
                    if kind == "attr" and isinstance(t, ast.Attribute) \
                            and t.attr == name:
                        return True
                    if kind == "name" and isinstance(t, ast.Name) \
                            and t.id == name:
                        return True
        return False
