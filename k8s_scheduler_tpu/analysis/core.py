"""schedlint core: source model, findings, suppressions, baseline.

A `Finding` is (file, line, code, message). Its *identity* for baseline
purposes is (file, code, message) — line numbers churn with unrelated
edits, so a committed baseline entry grandfathers a finding wherever it
moves within its file as long as the message is unchanged.

Suppression syntax (checked on the finding's own line):

    something_flagged()  # schedlint: disable=TS001
    another_thing()      # schedlint: disable=TS002,LD002 -- why it's ok
    legacy_module_wide   # schedlint: disable-file=HY001 (anywhere in file)

`disable=all` silences every code on that line. A suppression SHOULD
carry a trailing justification; the framework doesn't parse it, review
does.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
import tokenize
from io import StringIO
from typing import Any, Iterable

# codes are letter(s)+digits (TS001) or the literal `all`; the list
# stops at the first non-code token so a justification written without
# the `--` separator can't silently void the suppression
_SUPPRESS_RE = re.compile(
    r"#\s*schedlint:\s*(disable|disable-file)="
    r"((?:[A-Za-z]+\d+|all)(?:\s*,\s*(?:[A-Za-z]+\d+|all))*)"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    file: str  # repo-relative, forward slashes
    line: int  # 1-indexed
    code: str  # e.g. "TS001"
    message: str

    def key(self) -> tuple[str, str, str]:
        """Baseline identity: line-independent (see module docstring)."""
        return (self.file, self.code, self.message)

    def fingerprint(self) -> str:
        """Stable finding id for cross-PR diffing: a short digest of the
        line-independent baseline identity (file-relative, so a repo
        checked out anywhere produces the same fingerprint). Two
        identical findings share a fingerprint — diff tools count
        occurrences, exactly like apply_baseline does."""
        return hashlib.sha256(
            "|".join(self.key()).encode()
        ).hexdigest()[:12]

    def to_dict(self) -> dict[str, Any]:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.code} {self.message}"


class SourceFile:
    """One parsed module: path, text, AST, and its suppression table.

    Parsed exactly once per distinct content per process — load_tree
    serves repeats from a content-verified module-level cache, so a
    multi-invocation session (the `--changed` pre-commit loop, the
    fixture-heavy test suite) never re-parses an unchanged file.
    `walk()` is the shared whole-tree node list every pass iterates
    instead of re-running `ast.walk` per pass."""

    def __init__(self, path: str, rel: str, text: str) -> None:
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text, filename=rel)
        self._nodes: list[ast.AST] | None = None
        # line -> set of codes (or {"all"}); "file" key = whole-file codes
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        self._parse_suppressions()

    def walk(self) -> list[ast.AST]:
        """Every AST node of the module, in `ast.walk` order, computed
        once and shared by all passes (read-only by contract)."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    @property
    def module(self) -> str:
        """Dotted module name relative to the scanned root."""
        mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        mod = mod.replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        return mod

    def _parse_suppressions(self) -> None:
        # tokenize so a '#' inside a string literal can't fake a pragma
        try:
            tokens = tokenize.generate_tokens(StringIO(self.text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                codes = {
                    c.strip() for c in m.group(2).split(",") if c.strip()
                }
                if m.group(1) == "disable-file":
                    self.file_suppressions |= codes
                else:
                    self.line_suppressions.setdefault(
                        tok.start[0], set()
                    ).update(codes)
        except tokenize.TokenError:
            pass  # ast.parse already accepted it; pragmas best-effort

    def suppressed(self, line: int, code: str) -> bool:
        if {code, "all"} & self.file_suppressions:
            return True
        codes = self.line_suppressions.get(line)
        return bool(codes and ({code, "all"} & codes))


# the default lint roots — ALSO consumed by scripts/schedlint.py's
# --changed filter, so the two surfaces cannot drift
DEFAULT_PATHS = ("k8s_scheduler_tpu", "scripts")


def load_tree(
    root: str, paths: Iterable[str] | None = None
) -> list[SourceFile]:
    """Parse every .py under `paths` (files or directories, relative to
    `root`; default: the k8s_scheduler_tpu package + scripts/)."""
    root = os.path.abspath(root)
    if paths is None:
        paths = DEFAULT_PATHS
    out: list[SourceFile] = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(full):
            out.append(_load_one(root, full))
            continue
        if not os.path.isdir(full):
            # a typo'd path silently scanning 0 files would turn the
            # lint permanently green; fail loudly instead
            raise FileNotFoundError(
                f"schedlint: path {p!r} does not exist under {root}"
            )
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in ("__pycache__", ".git", ".pytest_cache")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    out.append(_load_one(root, os.path.join(dirpath, name)))
    return out


# (abs path, rel) -> SourceFile: the process-wide parse cache. The hit
# test compares the just-read TEXT against the cached one — stat-only
# identity (mtime_ns, size) can serve a stale AST when a same-length
# rewrite lands within one filesystem timestamp tick, and the read is
# cheap next to the parse + suppression scan it saves. Bounded LRU
# (refresh-on-hit): fixture-heavy test runs lint hundreds of throwaway
# tmp-dir trees whose keys never hit again — without the cap they (and
# their walk()-materialized node lists) would pin memory for the whole
# process, and without the refresh they would evict the live repo.
_PARSE_CACHE: dict[tuple[str, str], SourceFile] = {}
_PARSE_CACHE_CAP = 1024


def _load_one(root: str, full: str) -> SourceFile:
    rel = os.path.relpath(full, root)
    with open(full, encoding="utf-8") as f:
        text = f.read()
    key = (os.path.abspath(full), rel)
    hit = _PARSE_CACHE.get(key)
    if hit is not None and hit.text == text:
        del _PARSE_CACHE[key]
        _PARSE_CACHE[key] = hit
        return hit
    sf = SourceFile(full, rel, text)
    _PARSE_CACHE[key] = sf
    while len(_PARSE_CACHE) > _PARSE_CACHE_CAP:
        _PARSE_CACHE.pop(next(iter(_PARSE_CACHE)))
    return sf


class LintContext:
    """What a pass gets to look at: the parsed file set + the shared
    call-graph index (built lazily — only passes that walk reachability
    pay for it)."""

    def __init__(self, root: str, files: list[SourceFile]) -> None:
        self.root = os.path.abspath(root)
        self.files = files
        self._by_rel = {f.rel: f for f in files}
        self._by_module = {f.module: f for f in files}
        self._index = None
        self._effects = None

    def file(self, rel: str) -> SourceFile | None:
        return self._by_rel.get(rel.replace(os.sep, "/"))

    def module(self, name: str) -> SourceFile | None:
        return self._by_module.get(name)

    @property
    def index(self):
        if self._index is None:
            from .callgraph import CodeIndex

            self._index = CodeIndex(self.files)
        return self._index

    @property
    def effects(self):
        """The shared interprocedural effect engine (lazy, like the
        call-graph index it stands on): JIT-PURITY and
        DURABILITY-ORDER both read it, fixpoint summaries and the
        traced region are computed once per lint run."""
        if self._effects is None:
            from .effects import EffectEngine

            self._effects = EffectEngine(self.index)
        return self._effects


# ---- baseline ------------------------------------------------------------


def load_baseline(path: str) -> list[dict]:
    """The committed grandfather list: [{"file", "code", "message",
    "count"?}, ...]. A missing file is an empty baseline."""
    if not path or not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("findings", []))


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    """One entry per (file, code, message) identity, carrying an
    explicit "count" when the same identity occurs more than once —
    the count IS the grandfather budget, so a second identical
    violation added later is new, not silently absorbed."""
    counts: dict[tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = []
    for (file, code, message), n in sorted(counts.items()):
        e: dict[str, Any] = {"file": file, "code": code,
                             "message": message}
        if n > 1:
            e["count"] = n
        entries.append(e)
    data = {
        "comment": (
            "schedlint grandfathered findings — entries match on "
            "(file, code, message), line-independent and count-aware "
            "(the optional \"count\" is the budget for identical "
            "findings; absent = 1). Regenerate with "
            "scripts/schedlint.py --write-baseline; shrink it, don't "
            "grow it."
        ),
        "findings": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=False)
        f.write("\n")


def apply_baseline(
    findings: list[Finding], baseline: list[dict]
) -> tuple[list[Finding], list[Finding]]:
    """Split into (new, grandfathered). Matching is count-aware: an
    entry grandfathers at most its "count" (default 1) identical
    findings, so a SECOND identical violation in the same file is
    reported as new instead of riding the first one's entry."""
    budget: dict[tuple[str, str, str], int] = {}
    for e in baseline:
        k = (e.get("file", ""), e.get("code", ""), e.get("message", ""))
        budget[k] = budget.get(k, 0) + int(e.get("count", 1))
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


def stale_baseline_entries(
    baseline: list[dict], grandfathered: list[Finding]
) -> list[tuple[tuple[str, str, str], int]]:
    """Baseline budget that matched nothing this run: [(identity,
    leftover), ...] — the entries --fail-on-new nags about so the
    baseline shrinks instead of fossilizing."""
    budget: dict[tuple[str, str, str], int] = {}
    for e in baseline:
        k = (e.get("file", ""), e.get("code", ""), e.get("message", ""))
        budget[k] = budget.get(k, 0) + int(e.get("count", 1))
    for f in grandfathered:
        budget[f.key()] -= 1
    return sorted(
        (k, left) for k, left in budget.items() if left > 0
    )


# ---- driver --------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # unsuppressed, non-baselined (the failures)
    suppressed: list[Finding]
    grandfathered: list[Finding]
    files_scanned: int
    passes_run: list[str]

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict[str, Any]:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "passes": self.passes_run,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "grandfathered": [f.to_dict() for f in self.grandfathered],
        }


def to_sarif(result: LintResult, rules: dict[str, str]) -> dict[str, Any]:
    """SARIF 2.1.0 for CI annotation UIs: new findings at error level,
    suppressed/grandfathered carried along with their suppression kind
    (inSource = an inline pragma, external = the baseline file) so a
    viewer can show them greyed out instead of losing them. `rules` is
    code -> description (registry.all_codes)."""

    def _result(f: Finding, level: str, suppression: str | None) -> dict:
        r: dict[str, Any] = {
            "ruleId": f.code,
            "level": level,
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.file},
                    "region": {"startLine": f.line},
                },
            }],
            "partialFingerprints": {
                "schedlintFingerprint/v1": f.fingerprint(),
            },
        }
        if suppression is not None:
            r["suppressions"] = [{"kind": suppression}]
        return r

    results = (
        [_result(f, "error", None) for f in result.findings]
        + [_result(f, "note", "inSource") for f in result.suppressed]
        + [_result(f, "note", "external") for f in result.grandfathered]
    )
    return {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "schedlint",
                    "informationUri": "README.md#static-analysis",
                    "rules": [
                        {
                            "id": code,
                            "shortDescription": {"text": desc},
                        }
                        for code, desc in sorted(rules.items())
                    ],
                },
            },
            "results": results,
        }],
    }


def run_lint(
    root: str,
    paths: Iterable[str] | None = None,
    registry=None,
    passes: Iterable[str] | None = None,
    pass_args: dict[str, dict] | None = None,
    baseline_path: str | None = None,
) -> LintResult:
    """Parse, run the (selected) passes, apply suppressions + baseline."""
    from .registry import default_registry

    registry = registry or default_registry()
    files = load_tree(root, paths)
    ctx = LintContext(root, files)
    names = list(passes) if passes else registry.names()
    pass_args = pass_args or {}
    raw: list[Finding] = []
    for name in names:
        p = registry.make(name, pass_args.get(name))
        raw.extend(p.run(ctx))
    raw.sort(key=lambda f: (f.file, f.line, f.code, f.message))

    live: list[Finding] = []
    suppressed: list[Finding] = []
    for f in raw:
        sf = ctx.file(f.file)
        if sf is not None and sf.suppressed(f.line, f.code):
            suppressed.append(f)
        else:
            live.append(f)

    baseline = load_baseline(baseline_path) if baseline_path else []
    new, grandfathered = apply_baseline(live, baseline)
    return LintResult(
        findings=new,
        suppressed=suppressed,
        grandfathered=grandfathered,
        files_scanned=len(files),
        passes_run=names,
    )
