"""DURABILITY-ORDER (DO0xx): journal-before-mutate, barrier-before-ack.

The WAL contract (PR 13): every mutation of tracked scheduler state
appends its journal record FIRST (the journaled queue/cache mutators do
this internally, under their own lock), and a Submit is acknowledged
only after `ack_barrier()` proves the records that admitted it are on
disk. This pass walks the statement flow of every function under
`service/`, `state/`, and `tenancy/` (the durability perimeter) with
the effect engine's interprocedural summaries folded in at call sites:

- DO001  a tracked-store write (queue/cache WAL containers — _active,
         _bound, ...) reachable on a path with no preceding journal
         append: crash here and replay diverges from memory
- DO002  a SubmitResult acknowledging accepted work constructed on a
         path with no preceding ack_barrier(): the client is told
         "accepted" before the WAL proves it
- DO003  a broad handler swallows (no re-raise) over a try body that
         both journals and mutates: an exception between the two
         strands a half-applied transaction that replay will re-apply
         differently

Precision model (deliberate, documented):

- Branch joins are optimistic (union of branches): a mutation is
  flagged only when NO path establishes the journal first. The guard
  `if self._durable is not None: durable = ...ack_barrier()` in
  service/admission.py therefore counts as an ack.
- Exception edges are pessimistic: an except handler starts from the
  pre-try state (any effect inside the try may not have happened).
- A call that the engine proves journals-and-mutates (the journaled
  funnel, e.g. `self.queue.add`) is atomic-and-safe and establishes
  `journal` for the rest of the path.
- Call-carried mutations (via the callee's summary) are flagged at the
  call site only when the callee is OUTSIDE the durability perimeter —
  an in-perimeter callee is analyzed directly, and flagging both
  would double-report one bug.
"""

from __future__ import annotations

import ast

from .callgraph import FuncInfo, attribute_chain
from .core import Finding, LintContext
from .effects import EffectEngine, _store_effects
from .registry import PassBase

_SCOPE_SEGMENTS = frozenset({"service", "state", "tenancy"})

_BROAD = frozenset({"Exception", "BaseException"})


def _in_scope(rel: str) -> bool:
    return bool(_SCOPE_SEGMENTS & set(rel.split("/")[:-1]))


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        chain = attribute_chain(n)
        if chain and chain[-1] in _BROAD:
            return True
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    return not any(
        isinstance(n, ast.Raise) for n in ast.walk(handler)
    )


class DurabilityOrderPass(PassBase):
    name = "DURABILITY-ORDER"
    codes = {
        "DO001": "tracked-state mutation with no preceding journal "
                 "append on some path",
        "DO002": "Submit acknowledged with no preceding durability "
                 "barrier on some path",
        "DO003": "broad handler swallows between journal append and "
                 "state mutation (half-applied transaction survives)",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        engine: EffectEngine = ctx.effects
        index = ctx.index
        out: list[Finding] = []
        for fid in sorted(index.funcs):
            f = index.funcs[fid]
            if not _in_scope(f.file.rel):
                continue
            if isinstance(f.node, ast.Lambda):
                continue
            self._scan_function(engine, f, out)
        return out

    # ---- per-function flow walk ------------------------------------------

    def _scan_function(
        self, engine: EffectEngine, f: FuncInfo, out: list[Finding]
    ) -> None:
        self._walk(engine, f, list(f.node.body), set(), out)

    def _walk(
        self,
        engine: EffectEngine,
        f: FuncInfo,
        stmts: list[ast.stmt],
        est: set[str],
        out: list[Finding],
    ) -> set[str]:
        """Forward walk: `est` is the set of effects established on
        every path into the current statement ('journal', 'ack').
        Returns the state after the block."""
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                self._exprs(engine, f, [stmt.test], est, out)
                a = self._walk(engine, f, stmt.body, set(est), out)
                b = self._walk(engine, f, stmt.orelse, set(est), out)
                est = a | b  # optimistic join (see module docstring)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                iters = [stmt.iter] if isinstance(
                    stmt, (ast.For, ast.AsyncFor)
                ) else [stmt.test]
                self._exprs(engine, f, iters, est, out)
                body = self._walk(engine, f, stmt.body, set(est), out)
                els = self._walk(engine, f, stmt.orelse, set(est), out)
                est = est | body | els
            elif isinstance(stmt, ast.Try):
                self._check_try(engine, f, stmt, est, out)
                body = self._walk(engine, f, stmt.body, set(est), out)
                after = set(body)
                for h in stmt.handlers:
                    # pessimistic: the try may have failed before any
                    # of its effects happened
                    after |= self._walk(
                        engine, f, h.body, set(est), out
                    )
                after |= self._walk(engine, f, stmt.orelse, set(body), out)
                est = self._walk(
                    engine, f, stmt.finalbody, after, out
                )
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._exprs(
                    engine, f,
                    [i.context_expr for i in stmt.items], est, out,
                )
                est = self._walk(engine, f, stmt.body, est, out)
            elif isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue  # its own frame; analyzed separately
            else:
                self._stmt_events(engine, f, stmt, est, out)
        return est

    def _stmt_events(
        self,
        engine: EffectEngine,
        f: FuncInfo,
        stmt: ast.stmt,
        est: set[str],
        out: list[Finding],
    ) -> None:
        # value expressions first (they evaluate before the store)
        self._exprs(
            engine, f, list(ast.iter_child_nodes(stmt)), est, out,
        )
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            if isinstance(stmt, ast.AnnAssign) and stmt.value is None:
                targets = []
        elif isinstance(stmt, ast.Delete):
            targets = stmt.targets
        else:
            return
        for t in targets:
            for kind, detail in _store_effects(t, stmt.lineno):
                if kind == "mutation" and "journal" not in est:
                    out.append(Finding(
                        f.file.rel, stmt.lineno, "DO001",
                        f"{f.qualname} writes tracked store "
                        f"`{detail.rstrip(' =')}` with no journal "
                        "append on this path: a crash here leaves "
                        "memory ahead of the WAL, and replay "
                        "diverges (journal first, or go through the "
                        "journaled queue/cache mutators)",
                    ))

    def _exprs(
        self,
        engine: EffectEngine,
        f: FuncInfo,
        exprs: list[ast.AST],
        est: set[str],
        out: list[Finding],
    ) -> None:
        """Classify every call in the given expressions (source order),
        update `est`, and emit DO001/DO002 hazards."""
        stack = [e for e in reversed(exprs) if isinstance(e, ast.expr)]
        calls: list[ast.Call] = []
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue  # its own frame
            if isinstance(node, ast.Call):
                calls.append(node)
            stack.extend(
                c for c in reversed(list(ast.iter_child_nodes(node)))
                if isinstance(c, ast.expr)
            )
        calls.sort(key=lambda c: (c.lineno, c.col_offset))
        for node in calls:
            kinds = engine.call_kinds(f, node)
            chain = attribute_chain(node.func)
            if chain and chain[-1] == "SubmitResult":
                self._check_submit(f, node, est, out)
            if "mutation" in kinds and "journal" not in kinds and (
                "journal" not in est
            ):
                detail, hop = kinds["mutation"]
                if hop is None:
                    where = f"`{detail}`"
                    flag = True
                else:
                    where = f"call into {hop} (reaches `{detail}`)"
                    # in-perimeter callees are analyzed directly;
                    # flagging the call site too would double-report
                    hop_in_scope = any(
                        _in_scope(self.index_rel(engine, t))
                        for t in sorted(
                            engine.index.resolve_callback(f, node.func)
                        )
                    )
                    flag = not hop_in_scope
                if flag:
                    out.append(Finding(
                        f.file.rel, node.lineno, "DO001",
                        f"{f.qualname} mutates tracked state via "
                        f"{where} with no journal append on this "
                        "path: a crash here leaves memory ahead of "
                        "the WAL (journal first, or go through the "
                        "journaled queue/cache mutators)",
                    ))
            if "journal" in kinds:
                est.add("journal")
            if "ack" in kinds:
                est.add("ack")

    @staticmethod
    def index_rel(engine: EffectEngine, fid: str) -> str:
        info = engine.index.funcs.get(fid)
        return info.file.rel if info is not None else ""

    def _check_submit(
        self,
        f: FuncInfo,
        node: ast.Call,
        est: set[str],
        out: list[Finding],
    ) -> None:
        acked = False
        for kw in node.keywords:
            if kw.arg == "accepted" and not (
                isinstance(kw.value, ast.Constant)
                and not kw.value.value
            ):
                acked = True
            if kw.arg == "durable" and (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is True
            ):
                acked = True
        if acked and "ack" not in est:
            out.append(Finding(
                f.file.rel, node.lineno, "DO002",
                f"{f.qualname} acknowledges accepted work "
                "(SubmitResult) with no ack_barrier() on this path: "
                "the client is told \"accepted\" before the WAL "
                "records that admitted it are proven on disk "
                "(acked => durable, PR 13)",
            ))

    def _check_try(
        self,
        engine: EffectEngine,
        f: FuncInfo,
        stmt: ast.Try,
        est: set[str],
        out: list[Finding],
    ) -> None:
        """DO003: a broad swallowing handler over a try body that both
        journals and mutates — an exception between the two strands a
        half-applied transaction."""
        journal_at: int | None = None
        mutate_at: int | None = None
        for sub in stmt.body:
            kinds = self._block_kinds(engine, f, sub)
            if "journal" in kinds and journal_at is None:
                journal_at = sub.lineno
            if "mutation" in kinds and mutate_at is None:
                mutate_at = sub.lineno
        if journal_at is None or mutate_at is None:
            return
        if journal_at == mutate_at:
            return  # one atomic funnel call (journaled mutator)
        for h in stmt.handlers:
            if _is_broad_handler(h) and _swallows(h):
                out.append(Finding(
                    f.file.rel, h.lineno, "DO003",
                    f"broad handler in {f.qualname} swallows over a "
                    f"try that journals (line {journal_at}) and "
                    f"mutates (line {mutate_at}): an exception "
                    "between the two strands a half-applied "
                    "transaction that replay re-applies differently "
                    "— narrow the except or re-raise after cleanup",
                ))

    def _block_kinds(
        self, engine: EffectEngine, f: FuncInfo, stmt: ast.stmt
    ) -> set[str]:
        """Effect kinds a statement (including nested blocks, but not
        nested function frames) may perform — textual + summaries."""
        kinds: set[str] = set()
        stack: list[ast.AST] = [stmt]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))
            if isinstance(node, ast.Call):
                kinds.update(engine.call_kinds(f, node))
            elif isinstance(
                node, (ast.Assign, ast.AugAssign, ast.AnnAssign)
            ):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    kinds.update(
                        k for k, _ in _store_effects(t, node.lineno)
                    )
        return kinds
