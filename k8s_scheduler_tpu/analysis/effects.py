"""Interprocedural effect engine for schedlint (v3).

Per-function *effect summaries* propagated to a fixpoint over the
callgraph.py call graph, plus the computed *traced region* (every
function reachable from a jit/vmap entry point). Two pass families
stand on this engine — JIT-PURITY (jit_purity.py) and DURABILITY-ORDER
(durability_order.py) — and TRACE-SAFETY's root discovery delegates
here so the three cannot disagree about what is traced.

Effect kinds:

    io          host I/O: open/os.*/shutil/socket/subprocess/logging/print
    time        clock read (time.*, datetime.now/utcnow/today/fromtimestamp)
    rng         host RNG (random.*, numpy.random.*)
    lock        lock/condition acquired (`with self._lock:` / .acquire())
    journal     WAL append (self._journal/_emit/_append_record,
                journal.append, or a journaled queue/cache mutator)
    ack         durability barrier (ack_barrier)
    metrics     metric emit (.labels(...).inc/observe/set, counter.inc)
    self_write  attribute written on self/cls
    mutation    write into a tracked WAL-backed container (_active,
                _bound, ... — see TRACKED_STORES)
    global_write  `global` declaration

Precision model (documented so pass authors know what they stand on):

- Direct effects are extracted textually from a function's own frame
  (`own_body_nodes`); nested defs/lambdas carry their own effects.
- Summaries union a function's direct effects with every reference's
  summary (callgraph's deliberately over-approximate resolution: a
  callback passed counts as called). A summary entry records the
  concrete detail plus the first callee hop it arrived through.
- journal/mutation classification is *textual* on attribute chains
  (`self.queue.add`, `self._journal`, `journal.append`): the call
  graph cannot resolve generic container-method names (`add`,
  `update` are in callgraph._GENERIC_ATTRS by design), so the WAL
  funnel is recognized by shape, not resolution. A journaled mutator
  (queue/cache public method) counts as journal AND mutation — it
  appends before it mutates, under its own lock, by contract.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import deque
from typing import Iterable

from .callgraph import CodeIndex, FuncInfo, attribute_chain, own_body_nodes
from .core import SourceFile

# ---- traced-root vocabulary (shared with trace_safety.py) ----------------

# the PluginBase hooks that are traced inside the cycle programs
TRACED_PLUGIN_METHODS = frozenset({
    "static_mask", "static_score", "dyn_mask", "dyn_score",
    "extra_init", "extra_update", "dyn_mask_batched", "dyn_score_batched",
    "extra_update_batched", "score_node_anchor", "post_filter",
})

# names whose call wraps its first argument in a compiled program; _jit
# is the repo's resilient wrapper in core/cycle.py, vmap callbacks are
# traced by the batching transform exactly like jit callbacks
JIT_NAMES = frozenset({"jit", "pjit", "pmap", "_jit"})
TRACE_CALL_NAMES = JIT_NAMES | frozenset({"vmap"})

_DATETIME_IMPURE = frozenset({"now", "utcnow", "today", "fromtimestamp"})

# real module name -> canonical tag for the alias table
ALIAS_TARGETS = {
    "time": "time",
    "datetime": "datetime",
    "random": "random",
    "numpy": "np",
    "jax.numpy": "jnp",
    "os": "os",
    "shutil": "shutil",
    "socket": "socket",
    "subprocess": "subprocess",
    "logging": "logging",
    "uuid": "uuid",
}

# modules whose bare-name from-imports we track (`from time import
# monotonic` -> the bound name carries the effect)
_BARE_NAME_TAGS = frozenset({
    "time", "random", "os", "socket", "subprocess", "shutil", "uuid",
})


def module_aliases(sf: SourceFile, targets: dict[str, str]) -> dict:
    """alias -> canonical target for stdlib-ish modules we care about
    (`targets` maps real module name -> canonical tag)."""
    out: dict[str, str] = {}
    for node in sf.walk():
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in targets:
                    out[a.asname or a.name.split(".")[0]] = targets[a.name]
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0 and node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":  # from jax import numpy as jnp
                        out[a.asname or a.name] = "jnp"
            elif node.level == 0 and node.module in targets:
                tag = targets[node.module]
                for a in node.names:
                    if tag in _BARE_NAME_TAGS:
                        # from time import monotonic -> bare-name call
                        out[a.asname or a.name] = f"{tag}.{a.name}"
                    elif tag == "datetime":
                        # from datetime import datetime/date: the bound
                        # class carries the impure .now()/.today()
                        out[a.asname or a.name] = "datetime"
    return out


def is_jit_expr(expr: ast.AST) -> bool:
    """True for `jax.jit`, `@jit`, `@partial(jax.jit, ...)` shapes."""
    chain = attribute_chain(expr)
    if chain and chain[-1] in TRACE_CALL_NAMES:
        return True
    if isinstance(expr, ast.Call):
        fchain = attribute_chain(expr.func)
        if fchain and fchain[-1] in TRACE_CALL_NAMES:
            return True  # @jax.jit(static_argnums=...) factory form
        if fchain and fchain[-1] == "partial" and expr.args:
            achain = attribute_chain(expr.args[0])
            return bool(achain and achain[-1] in TRACE_CALL_NAMES)
    return False


def jit_call_targets(index: CodeIndex, f, node: ast.Call) -> set[str]:
    """Function ids traced by a `jit(...)`/`vmap(...)` call expression."""
    chain = attribute_chain(node.func)
    if not chain or chain[-1] not in TRACE_CALL_NAMES or not node.args:
        return set()
    # jax.jit(fn) / jax.jit(partial(fn, ...)) / jax.jit(lambda ...):
    # the one shared callback-resolution ladder (callgraph.py) —
    # Thread targets and observer registrations resolve identically
    return index.resolve_callback(f, node.args[0])


def module_shim(sf: SourceFile) -> FuncInfo:
    """A FuncInfo standing in for module scope, so module-level
    `cycle = jax.jit(fn)` resolves through the same ladder."""
    return FuncInfo(
        id=f"{sf.rel}::<module>", file=sf, node=sf.tree,
        name="<module>", qualname="<module>", cls=None,
        parent=None, lineno=1,
    )


def traced_roots(index: CodeIndex) -> dict[str, str]:
    """Every jit/vmap entry point: function id -> a short witness label
    of WHY it is a root (for pass messages)."""
    roots: dict[str, str] = {}

    def note(fid: str, label: str) -> None:
        roots.setdefault(fid, label)

    # 1) first argument of jit-wrapping calls — inside any function,
    #    and at module scope (`cycle = jax.jit(fn)` in a script)
    for f in sorted(index.funcs.values(), key=lambda i: i.id):
        for node in own_body_nodes(f.node):
            if isinstance(node, ast.Call):
                chain = attribute_chain(node.func)
                for fid in sorted(jit_call_targets(index, f, node)):
                    note(fid, f"{'.'.join(chain)}() at "
                              f"{f.file.rel}:{node.lineno}")
    for sf in index.files:
        shim = module_shim(sf)
        for node in own_body_nodes(sf.tree):
            if isinstance(node, ast.Call):
                chain = attribute_chain(node.func)
                for fid in sorted(jit_call_targets(index, shim, node)):
                    note(fid, f"{'.'.join(chain)}() at "
                              f"{sf.rel}:{node.lineno}")
    # 2) decorator-form jit: @jax.jit / @jit / @partial(jax.jit, ..)
    for fid in sorted(index.funcs):
        f = index.funcs[fid]
        node = f.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(is_jit_expr(d) for d in node.decorator_list):
                note(fid, f"@jit on {f.qualname}")
    # 3) every compute hook of a PluginBase-derived class
    for ci in sorted(
        index.subclasses_of("PluginBase"), key=lambda c: (c.module, c.name)
    ):
        for mname, fid in sorted(ci.methods.items()):
            if mname in TRACED_PLUGIN_METHODS:
                note(fid, f"plugin hook {ci.name}.{mname}")
    return roots


# ---- effect vocabulary ---------------------------------------------------

# the WAL-backed containers of internal/queue.py + internal/cache.py;
# a write that bypasses their journaled mutators is a durability bug
TRACKED_STORES = frozenset({
    "_active", "_backoff", "_unschedulable", "_in_flight",
    "_deleted_in_flight", "_nodes", "_bound", "_assumed",
})

# public queue/cache mutators: they append their journal record before
# mutating, under their own lock — the WAL-correct funnel. The names
# unique to the queue/cache API match any queue/cache-ish receiver;
# the three that collide with dict/set methods (add/update/delete)
# require the receiver to literally be the queue, or `ctx._cache`
# memo-dict writes would read as the WAL funnel
JOURNALED_MUTATORS = frozenset({
    "pop_ready", "retire_in_flight",
    "requeue_backoff", "flush_backoff", "flush_unschedulable_timeout",
    "move_all_to_active_or_backoff", "recover_in_flight", "load_state",
    "add_node", "update_node", "remove_node", "add_pod", "remove_pod",
    "assume", "finish_binding", "confirm", "forget", "cleanup_expired",
})
_AMBIGUOUS_MUTATORS = frozenset({"add", "update", "delete"})
_QUEUE_SEGMENTS = frozenset({"queue", "_queue"})

_JOURNAL_FUNNELS = frozenset({
    "_journal", "_emit", "_emit_node", "_append_record",
})

_MUTATING_METHODS = frozenset({
    "pop", "popitem", "clear", "update", "setdefault", "append", "add",
    "remove", "discard", "insert", "extend", "move_to_end",
})

_LOCK_SUFFIXES = ("_lock", "_cond", "_condition")

_OS_IO = frozenset({
    "fsync", "replace", "rename", "unlink", "remove", "listdir",
    "makedirs", "open", "fdopen", "stat", "mkdir", "rmdir", "scandir",
    "walk", "close", "write", "read",
})

_LOG_METHODS = frozenset({
    "debug", "info", "warning", "error", "exception", "critical", "log",
})
_LOG_ROOTS = frozenset({"logging", "logger", "log", "_log", "_logger"})


@dataclasses.dataclass(frozen=True)
class Effect:
    kind: str
    detail: str  # concrete source shape, e.g. "self.queue.add()"
    line: int  # where it occurs, in the owning function's file


def _has_store_segment(chain: tuple[str, ...]) -> bool:
    return any(seg in TRACKED_STORES for seg in chain)


def _is_containerish(seg: str) -> bool:
    low = seg.lower()
    return "queue" in low or "cache" in low


def call_effects(
    chain: tuple[str, ...], aliases: dict[str, str]
) -> list[tuple[str, str]]:
    """Textual classification of one call's attribute chain into
    (kind, detail) pairs. Pure shape matching — see module docstring."""
    dotted = ".".join(chain)
    out: list[tuple[str, str]] = []
    last = chain[-1]
    tag = aliases.get(chain[0])

    if last == "ack_barrier":
        return [("ack", f"{dotted}()")]
    if last in _JOURNAL_FUNNELS and chain[0] in ("self", "cls"):
        return [("journal", f"{dotted}()")]
    if (
        last == "append" and len(chain) >= 2
        and any(seg in ("journal", "_journal", "wal", "_wal")
                for seg in chain[:-1])
    ):
        return [("journal", f"{dotted}()")]
    if len(chain) >= 2 and (
        (last in JOURNALED_MUTATORS
         and any(_is_containerish(seg) for seg in chain[:-1]))
        or (last in _AMBIGUOUS_MUTATORS
            and any(seg in _QUEUE_SEGMENTS for seg in chain[:-1]))
    ):
        # the journaled funnel: appends, then mutates, under its lock
        return [("journal", f"{dotted}()"), ("mutation", f"{dotted}()")]
    if last in _MUTATING_METHODS and _has_store_segment(chain[:-1]):
        out.append(("mutation", f"{dotted}()"))
    if last == "acquire" and len(chain) >= 2 and (
        chain[-2].endswith(_LOCK_SUFFIXES)
    ):
        out.append(("lock", f"{dotted}()"))

    if chain == ("print",):
        out.append(("io", "print"))
    elif chain == ("open",):
        out.append(("io", "open()"))
    elif tag == "os" and len(chain) > 1 and chain[-1] in _OS_IO:
        out.append(("io", f"os.{chain[-1]}()"))
    elif tag in ("socket", "subprocess", "shutil") and len(chain) > 1:
        out.append(("io", f"{tag}.{chain[-1]}()"))
    elif tag == "logging" and len(chain) > 1:
        out.append(("io", f"logging.{chain[-1]}()"))
    elif chain[0] in _LOG_ROOTS and last in _LOG_METHODS:
        out.append(("io", f"{dotted}()"))
    elif tag and "." in tag and len(chain) == 1:
        # bare name bound by `from <mod> import <name>`
        base = tag.split(".", 1)[0]
        if base in ("socket", "subprocess", "shutil"):
            out.append(("io", f"{tag}()"))
        elif base == "os" and tag.split(".", 1)[1] in _OS_IO:
            out.append(("io", f"{tag}()"))
        elif base == "time":
            out.append(("time", f"{tag}()"))
        elif base == "random":
            out.append(("rng", f"{tag}()"))
    elif tag == "time" and len(chain) > 1:
        out.append(("time", f"time.{chain[-1]}()"))
    elif tag == "datetime" and last in _DATETIME_IMPURE:
        out.append(("time", f"datetime.{last}()"))
    elif tag == "random" and len(chain) > 1:
        out.append(("rng", f"random.{chain[-1]}()"))
    elif tag == "np" and len(chain) >= 3 and chain[1] == "random":
        out.append(("rng", f"numpy.random.{chain[-1]}()"))
    return out


def _store_effects(
    target: ast.AST, line: int
) -> list[tuple[str, str]]:
    """Effects of one assignment/delete TARGET."""
    sub = isinstance(target, ast.Subscript)
    node = target.value if sub else target
    chain = attribute_chain(node)
    if chain is None:
        return []
    dotted = ".".join(chain) + ("[...]" if sub else "")
    out: list[tuple[str, str]] = []
    if _has_store_segment(chain):
        out.append(("mutation", f"{dotted} ="))
    if chain[0] in ("self", "cls") and len(chain) >= 2:
        out.append(("self_write", f"{dotted} ="))
    return out


def direct_effects(f: FuncInfo, aliases: dict[str, str]) -> tuple:
    """The effects f performs in its own frame (nested defs excluded)."""
    out: list[Effect] = []

    def emit(pairs: Iterable[tuple[str, str]], line: int) -> None:
        out.extend(Effect(kind, detail, line) for kind, detail in pairs)

    for node in own_body_nodes(f.node):
        if isinstance(node, ast.Call):
            chain = attribute_chain(node.func)
            if chain is not None:
                emit(call_effects(chain, aliases), node.lineno)
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("inc", "observe", "set")
                and isinstance(node.func.value, ast.Call)
                and isinstance(node.func.value.func, ast.Attribute)
                and node.func.value.func.attr == "labels"
            ):
                # family.labels(...).inc() — chain is rooted at a Call,
                # so attribute_chain is None; match the shape directly
                emit([("metrics",
                       f".labels(...).{node.func.attr}()")], node.lineno)
            if chain is not None and len(chain) >= 2 and (
                chain[-1] in ("inc", "observe")
                and any("metric" in seg.lower() for seg in chain[:-1])
            ):
                emit([("metrics", ".".join(chain) + "()")], node.lineno)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                chain = attribute_chain(item.context_expr)
                if chain and chain[-1].endswith(_LOCK_SUFFIXES):
                    emit([("lock", f"with {'.'.join(chain)}:")],
                         node.lineno)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            if isinstance(node, ast.AnnAssign) and node.value is None:
                targets = []
            for t in targets:
                emit(_store_effects(t, node.lineno), node.lineno)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                emit(_store_effects(t, node.lineno), node.lineno)
        elif isinstance(node, ast.Global):
            emit([("global_write", f"global {', '.join(node.names)}")],
                 node.lineno)
    return tuple(out)


# ---- the engine ----------------------------------------------------------


class EffectEngine:
    """Whole-program effect summaries + the traced region, computed
    lazily and shared by every pass through LintContext.effects."""

    def __init__(self, index: CodeIndex) -> None:
        self.index = index
        self._aliases: dict[str, dict[str, str]] = {}
        self._direct: dict[str, tuple] = {}
        self._call_refs: dict[str, frozenset[str]] = {}
        # fid -> kind -> (detail, first-callee-hop qualname | None)
        self._summaries: dict[str, dict[str, tuple[str, str | None]]] = {}
        self._summaries_built = False
        self._roots: dict[str, str] | None = None
        self._region: dict[str, tuple[str, ...]] | None = None

    def aliases_for(self, sf: SourceFile) -> dict[str, str]:
        hit = self._aliases.get(sf.rel)
        if hit is None:
            hit = module_aliases(sf, ALIAS_TARGETS)
            self._aliases[sf.rel] = hit
        return hit

    def direct(self, fid: str) -> tuple:
        hit = self._direct.get(fid)
        if hit is None:
            f = self.index.funcs[fid]
            hit = direct_effects(f, self.aliases_for(f.file))
            self._direct[fid] = hit
        return hit

    def call_references(self, f: FuncInfo) -> frozenset[str]:
        """Functions f may CALL: call targets, callback-position
        arguments (lax.scan/cond bodies, Thread targets), and nested
        lambdas. Narrower than CodeIndex.references on purpose — that
        one also follows bare attribute READS through the by-name
        fallback (`node.spec.unschedulable` would drag a method named
        `unschedulable` into the traced region), which is the right
        over-approximation for TRACE-SAFETY's import walk but smears
        effect summaries with never-executed frames."""
        hit = self._call_refs.get(f.id)
        if hit is not None:
            return hit
        index = self.index
        out: set[str] = set()
        for node in own_body_nodes(f.node):
            if not isinstance(node, ast.Call):
                continue
            out |= index.resolve_callback(f, node.func)
            for arg in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                out |= self._arg_targets(f, arg)
        for name, fid in index._children.get(f.id, {}).items():
            if name.startswith("<lambda"):
                out.add(fid)
        result = frozenset(out - {f.id})
        self._call_refs[f.id] = result
        return result

    def _arg_targets(self, f: FuncInfo, arg: ast.AST) -> set[str]:
        """A callback passed as an argument. Bare names resolve through
        the lexical ladder (no by-name fallback — safe); attribute
        chains resolve only when rooted at a module alias or a real
        `self.`/`cls.` method, because the by-name fallback would turn
        every data-attribute read passed to a builtin (`len(x.nodes)`)
        into a phantom call edge."""
        index = self.index
        if isinstance(arg, (ast.Name, ast.Lambda)):
            return index.resolve_callback(f, arg)
        if isinstance(arg, ast.Call):  # functools.partial(fn, ...)
            fchain = attribute_chain(arg.func)
            if fchain and fchain[-1] == "partial" and arg.args:
                return self._arg_targets(f, arg.args[0])
            return set()
        if isinstance(arg, ast.Attribute):
            chain = attribute_chain(arg)
            if chain is None:
                return set()
            if index._aliases.get(f.file.rel, {}).get(chain[0]):
                return index.resolve_chain(f, chain)
            if (
                chain[0] in ("self", "cls") and f.cls is not None
                and len(chain) == 2
            ):
                return index.class_method(f.module, f.cls, chain[1])
        return set()

    def summary(self, fid: str) -> dict[str, tuple[str, str | None]]:
        """kind -> (concrete detail, first callee hop or None if the
        effect is f's own). Fixpoint over the full call graph."""
        if not self._summaries_built:
            self._build_summaries()
        return self._summaries.get(fid, {})

    def _build_summaries(self) -> None:
        index = self.index
        refs = {
            fid: sorted(self.call_references(f))
            for fid, f in index.funcs.items()
        }
        rev: dict[str, set[str]] = {}
        for fid, rs in refs.items():
            for r in rs:
                rev.setdefault(r, set()).add(fid)
        summ: dict[str, dict[str, tuple[str, str | None]]] = {}
        for fid in index.funcs:
            summ[fid] = {
                e.kind: (e.detail, None) for e in self.direct(fid)
            }
        work = deque(sorted(index.funcs))
        queued = set(work)
        while work:
            fid = work.popleft()
            queued.discard(fid)
            s = summ[fid]
            changed = False
            for callee in refs[fid]:
                cs = summ.get(callee)
                if not cs:
                    continue
                hop = index.funcs[callee].qualname
                for kind, (detail, _via) in cs.items():
                    if kind not in s:
                        s[kind] = (detail, hop)
                        changed = True
            if changed:
                for caller in sorted(rev.get(fid, ())):
                    if caller not in queued:
                        queued.add(caller)
                        work.append(caller)
        self._summaries = summ
        self._summaries_built = True

    def call_kinds(
        self, f: FuncInfo, node: ast.Call
    ) -> dict[str, tuple[str, str | None]]:
        """Effect kinds one call expression may perform: the chain's
        textual classification unioned with the summaries of every
        function the callee expression resolves to."""
        out: dict[str, tuple[str, str | None]] = {}
        chain = attribute_chain(node.func)
        if chain is not None:
            for kind, detail in call_effects(chain, self.aliases_for(f.file)):
                out.setdefault(kind, (detail, None))
        for t in sorted(self.index.resolve_callback(f, node.func)):
            hop = self.index.funcs[t].qualname
            for kind, (detail, _via) in self.summary(t).items():
                out.setdefault(kind, (detail, hop))
        return out

    def traced_roots(self) -> dict[str, str]:
        if self._roots is None:
            self._roots = traced_roots(self.index)
        return self._roots

    def traced_region(self) -> dict[str, tuple[str, ...]]:
        """fid -> witness path of qualnames from a root to fid (roots
        map to a 1-element path). Deterministic BFS so finding messages
        are baseline-stable."""
        if self._region is not None:
            return self._region
        index = self.index
        region: dict[str, tuple[str, ...]] = {}
        q: deque[str] = deque()
        for fid in sorted(self.traced_roots()):
            if fid in index.funcs and fid not in region:
                region[fid] = (index.funcs[fid].qualname,)
                q.append(fid)
        while q:
            fid = q.popleft()
            path = region[fid]
            for ref in sorted(self.call_references(index.funcs[fid])):
                if ref not in region and ref in index.funcs:
                    region[ref] = path + (index.funcs[ref].qualname,)
                    q.append(ref)
        self._region = region
        return region
