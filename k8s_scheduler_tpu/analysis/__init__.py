"""schedlint: repo-native static analysis for the invariants this
codebase keeps rediscovering the hard way.

The scheduler is a JAX+threads hybrid: invariants like "no imports under
a trace" (PR 1's UnexpectedTracerError), the `queue -> cache -> journal`
lock order, and the journal's "one clock read, one record per mutator"
contract (state/manager.py) are load-bearing but invisible to Python
itself — upstream kube-scheduler gets the equivalent protection from
Go's race detector and vet. This package is the vet analogue: an
AST-based pass framework (registry mirroring framework/registry.py)
with inline `# schedlint: disable=CODE` suppressions and a committed
baseline for grandfathered findings, driven by scripts/schedlint.py and
a tier-1 test (tests/test_schedlint.py).

Passes (see each module's docstring for codes):

- TRACE-SAFETY   (trace_safety.py)    TS0xx — impure Python reachable
  from the jitted cycle programs / plugin compute fns
- JIT-PURITY     (jit_purity.py)      JP0xx — interprocedural effect
  summaries (effects.py) over the traced region: host effects under
  trace, unstable jit discriminators, jit wrappers built in loops
- LOCK-DISCIPLINE (lock_discipline.py) LD0xx — lock-order inversions and
  blocking calls under the scheduler's state locks
- JOURNAL-EMIT-ONCE (journal_emit.py)  JE0xx — the durable-state
  clock-once / record-once mutator contract
- DURABILITY-ORDER (durability_order.py) DO0xx — journal-before-mutate
  and barrier-before-ack, path-sensitively over service/state/tenancy
- INVENTORY-DRIFT (inventory.py)       ID0xx — metrics/config/CLI/README
  documentation drift (absorbs scripts/lint_metrics.py)
- HYGIENE        (hygiene.py)          HY0xx — unused module-level
  imports
- ROBUSTNESS     (robustness.py)       RB0xx — broad exception handlers
  must leave a trace (or carry an inventoried justification)
- THREADS        (threads.py)          TR003 — thread-role inference +
  spawned threads need a join/drain story
- RACES          (races.py)            TR001/2/4 — cross-role unlocked
  writes, whole-tree lock-order cycles, serve-loop blocking under
  contended locks
- TENANCY-ISOLATION (tenancy_isolation.py) TN001 — `_tn_*` per-tenant
  state stays behind the tenancy/ boundary
- SHARD-SAFETY   (shard_safety.py)     SH0xx — the PR 9 shard-exactness
  rules: argsel reduces, no axis-0 concat of sharded vectors, specs
  only via mesh_pin
"""

from .core import (
    Finding,
    LintContext,
    SourceFile,
    load_baseline,
    load_tree,
    run_lint,
    write_baseline,
)
from .registry import PassBase, PassRegistry, default_registry

__all__ = [
    "Finding",
    "LintContext",
    "PassBase",
    "PassRegistry",
    "SourceFile",
    "default_registry",
    "load_baseline",
    "load_tree",
    "run_lint",
    "write_baseline",
]
