"""JOURNAL-EMIT-ONCE (JE0xx): the durable-state mutator contract.

state/manager.py's replay exactness rests on a contract the queue and
cache docstrings state but nothing checks: every journaled public
mutator reads the clock EXACTLY ONCE, applies its change through
non-emitting internal helpers, and emits EXACTLY ONE record carrying
that clock value. Two clock reads can stamp a record with a time the
mutation didn't use (replay then derives different backoff/TTL
deadlines); two emission sites can double-apply an op on replay;
a clock read or emission inside an internal helper reintroduces both
hazards through composition.

Scope: every class that defines `set_journal` (the durable-state wiring
point — SchedulingQueue and SchedulerCache today, any future journaled
store automatically). Emission funnels (`_emit` / `_emit_node` methods)
are the sanctioned single emission/clock point and are exempt from the
helper rule; their reads/emits are charged to their callers.

- JE001  a journaled public mutator's clock-read count != 1
- JE002  a journaled public mutator has more than one emission site
- JE003  an internal helper (non-funnel `_`-method) reads the clock or
         emits a journal record
"""

from __future__ import annotations

import ast

from .callgraph import attribute_chain, own_body_nodes
from .core import Finding, LintContext
from .registry import PassBase

_FUNNELS = frozenset({"_emit", "_emit_node"})


class JournalEmitOncePass(PassBase):
    name = "JOURNAL-EMIT-ONCE"
    codes = {
        "JE001": "journaled mutator must read the clock exactly once",
        "JE002": "journaled mutator must emit exactly one record",
        "JE003": "internal helper must not read the clock or emit",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        index = ctx.index
        findings: list[Finding] = []
        for ci in index.classes.values():
            if "set_journal" not in ci.methods:
                continue
            findings.extend(self._check_class(index, ci))
        return findings

    def _check_class(self, index, ci) -> list[Finding]:
        funcs = {
            m: index.funcs[fid] for m, fid in ci.methods.items()
        }
        direct: dict[str, dict] = {}
        for m, f in funcs.items():
            direct[m] = self._direct_counts(f)

        # charge funnel clock reads / emissions to callers; fold in
        # self-calls to other emitting methods (memoized, cycle-safe)
        totals: dict[str, tuple[int, int]] = {}

        def total(m: str, seen: frozenset = frozenset()) -> tuple[int, int]:
            if m in totals:
                return totals[m]
            if m in seen or m not in direct:
                return (0, 0)
            d = direct[m]
            clock, emits = d["clock"], d["emits"]
            for callee, n in d["self_calls"].items():
                if callee == m or callee not in direct:
                    continue
                if callee in _FUNNELS:
                    c, e = direct[callee]["clock"], 1
                    # a funnel call IS one emission; its internal clock
                    # read is the mutator's one sanctioned read
                    clock += n * c
                    emits += n * e
                else:
                    c, e = total(callee, seen | {m})
                    clock += n * c
                    emits += n * e
            # cache only top-level results: a value computed under a
            # non-empty seen set may have had a cycle edge truncated to
            # (0, 0), and caching the undercount would leak it into the
            # callee's own top-level evaluation (mutually-recursive
            # mutators would then dodge JE001/JE002)
            if not seen:
                totals[m] = (clock, emits)
            return clock, emits

        findings: list[Finding] = []
        for m, f in sorted(funcs.items()):
            if m in _FUNNELS or m == "set_journal":
                continue
            if m.startswith("_"):
                d = direct[m]
                hemits = d["emits"] + sum(
                    n for c, n in d["self_calls"].items() if c in _FUNNELS
                )
                if d["clock"] or hemits:
                    what = []
                    if d["clock"]:
                        what.append(f"reads the clock {d['clock']}x")
                    if hemits:
                        what.append(f"emits {hemits} record(s)")
                    findings.append(Finding(
                        f.file.rel, f.lineno, "JE003",
                        f"internal helper {ci.name}.{m} "
                        f"{' and '.join(what)}: helpers must stay "
                        "non-emitting and clock-free so mutators "
                        "compose without double-stamping (durability "
                        "contract, state/manager.py)",
                    ))
                continue
            clock, emits = total(m)
            if emits == 0:
                continue  # not a journaled mutator
            if emits > 1:
                findings.append(Finding(
                    f.file.rel, f.lineno, "JE002",
                    f"journaled mutator {ci.name}.{m} has {emits} "
                    "journal emission sites: exactly one record per "
                    "public entry point, or replay double-applies",
                ))
            if clock != 1:
                findings.append(Finding(
                    f.file.rel, f.lineno, "JE001",
                    f"journaled mutator {ci.name}.{m} reads the clock "
                    f"{clock} times: the contract is ONE read whose "
                    "value both mutates state and stamps the record "
                    "(replay pins its clock to that t)",
                ))
        return findings

    @staticmethod
    def _direct_counts(f) -> dict:
        clock = 0
        emits = 0
        self_calls: dict[str, int] = {}
        for node in own_body_nodes(f.node):
            if not isinstance(node, ast.Call):
                continue
            chain = attribute_chain(node.func)
            if chain is None or len(chain) != 2 or chain[0] != "self":
                continue
            attr = chain[1]
            if attr == "_now":
                clock += 1
            elif attr == "_journal":
                emits += 1
            else:
                self_calls[attr] = self_calls.get(attr, 0) + 1
        return {"clock": clock, "emits": emits, "self_calls": self_calls}
