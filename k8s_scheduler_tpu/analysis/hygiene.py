"""HYGIENE (HY0xx): dead module-level names and script-layer sprawl.

The probe/profiling script layer accretes imports and private constants
that outlive the experiment that needed them; in the package they also
cost import time. Conservative by construction:

- HY001  a module-level import whose bound name is never referenced in
         the module (skipped in __init__.py — re-exports are the
         point — and for names listed in __all__)
- HY002  a module-level `_private` assignment never referenced again
         (underscore names only: public constants may be external API)
- HY003  scripts/ inventory drift: a `scripts/*.py` not named in
         SCRIPT_ALLOWLIST (one-off probe/bisect/trace scripts
         historically accumulated 25 deep before ISSUE 6 pruned them —
         adding a script now requires the deliberate act of listing it
         here), or an allowlist entry whose file no longer exists
"""

from __future__ import annotations

import ast
import re

from .core import Finding, LintContext
from .registry import PassBase

# The maintained scripts/ inventory. Everything here is referenced by
# the README, the test suite, or CI; a new script joins by being added
# HERE in the same commit (HY003 fails otherwise), which is the review
# hook that keeps dead one-off probes from accumulating silently again.
SCRIPT_ALLOWLIST = frozenset({
    "scripts/alerts_check.py",    # clean-soak alert-rule CI gate
    "scripts/audit_sharded.py",   # compile-only collective-budget gate
    "scripts/bench_diff.py",      # BENCH artifact CI tripwire
    "scripts/blackbox_read.py",   # crash black-box bundle reader
    "scripts/fuzz_scheduler.py",  # scenario-fuzzer differential soak
    "scripts/lint_metrics.py",    # metric-inventory shim (tests)
    "scripts/loadgen.py",         # open-loop front-door load generator
    "scripts/probe_pipeline.py",  # CPU-runnable pipeline smoke probe
    "scripts/schedlint.py",       # this framework's CLI
    "scripts/soak_chaos.py",      # slow-marked fault-injection chaos soak
    "scripts/soak_differential.py",  # slow-marked differential soak
    "scripts/soak_failover.py",   # slow-marked kill -9 failover soak
    "scripts/warm_cache.py",      # compile-cache pre-warmer (ops tool)
})


class HygienePass(PassBase):
    name = "HYGIENE"
    codes = {
        "HY001": "unused module-level import",
        "HY002": "dead private module-level constant",
        "HY003": "scripts/ inventory drift (not in SCRIPT_ALLOWLIST)",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        findings: list[Finding] = []
        seen_scripts: set[str] = set()
        for sf in ctx.files:
            rel = sf.rel.replace("\\", "/")
            if rel.startswith("scripts/"):
                seen_scripts.add(rel)
                if rel not in SCRIPT_ALLOWLIST:
                    findings.append(Finding(
                        sf.rel, 1, "HY003",
                        f"{rel} is not in analysis/hygiene.py's "
                        "SCRIPT_ALLOWLIST — list it deliberately or "
                        "remove the script (one-off probes accumulate)",
                    ))
            if sf.rel.endswith("__init__.py"):
                continue
            if sf.rel.endswith("_pb2.py"):
                continue  # generated protobuf output, not hand-written
            findings.extend(self._check(sf))
        # dangling allowlist entries: judged against the DISK, not the
        # scanned set — a path-scoped scan of one script must not
        # report every other (existing) entry as stale. Gated on the
        # scan having covered either scripts/ or this pass's own module
        # (any real-repo scan has one of the two): fixture trees that
        # contain neither must not be judged against the repo's
        # inventory, but "scripts/ was deleted wholesale while the
        # allowlist still names it" — seen_scripts empty — must be
        if seen_scripts or ctx.file(
            "k8s_scheduler_tpu/analysis/hygiene.py"
        ) is not None:
            import os

            for rel in sorted(SCRIPT_ALLOWLIST - seen_scripts):
                if not os.path.exists(os.path.join(ctx.root, rel)):
                    findings.append(Finding(
                        "k8s_scheduler_tpu/analysis/hygiene.py", 1,
                        "HY003",
                        f"SCRIPT_ALLOWLIST names {rel} but no such "
                        "file exists — remove the stale entry",
                    ))
        return findings

    def _check(self, sf) -> list[Finding]:
        tree = sf.tree
        used: set[str] = set()
        exported: set[str] = set()
        imported: dict[str, tuple[int, str]] = {}  # name -> (line, shown)
        assigned: dict[str, int] = {}
        multi_assigned: set[str] = set()

        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    imported[bound] = (node.lineno, a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    bound = a.asname or a.name
                    imported[bound] = (
                        node.lineno,
                        f"{'.' * node.level}{node.module or ''}.{a.name}",
                    )
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        if t.id in assigned:
                            multi_assigned.add(t.id)
                        assigned[t.id] = node.lineno
                        if t.id == "__all__":
                            for e in ast.walk(node.value):
                                if isinstance(e, ast.Constant) and \
                                        isinstance(e.value, str):
                                    exported.add(e.value)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                assigned[node.target.id] = node.lineno

        def _string_annotation(n: ast.AST | None) -> None:
            # quoted annotations ("Iterable[dict[str, float]]") hide
            # their names in a Constant; count every identifier inside
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                used.update(
                    re.findall(r"[A-Za-z_][A-Za-z0-9_]*", n.value)
                )

        class _Uses(ast.NodeVisitor):
            def visit_Name(self, n: ast.Name) -> None:
                if isinstance(n.ctx, ast.Load):
                    used.add(n.id)
                elif isinstance(n.ctx, ast.Store):
                    # a later module-level rebind doesn't "use" it, but
                    # a function-level `global x; x = ...` pattern pairs
                    # with a read somewhere to matter; keep Store out
                    pass
                self.generic_visit(n)

            def visit_Global(self, n: ast.Global) -> None:
                used.update(n.names)

            def visit_AnnAssign(self, n: ast.AnnAssign) -> None:
                _string_annotation(n.annotation)
                self.generic_visit(n)

            def visit_arg(self, n: ast.arg) -> None:
                _string_annotation(n.annotation)
                self.generic_visit(n)

            def _visit_fn(self, n) -> None:
                _string_annotation(n.returns)
                self.generic_visit(n)

            visit_FunctionDef = _visit_fn
            visit_AsyncFunctionDef = _visit_fn

        _Uses().visit(tree)

        findings = []
        for name, (line, shown) in sorted(imported.items()):
            if name in used or name in exported or name == "_":
                continue
            findings.append(Finding(
                sf.rel, line, "HY001",
                f"import {shown!r} binds {name!r}, never referenced in "
                "this module",
            ))
        for name, line in sorted(assigned.items()):
            if (
                not name.startswith("_") or name.startswith("__")
                or name in used or name in exported
                or name in multi_assigned or name in imported
            ):
                continue
            findings.append(Finding(
                sf.rel, line, "HY002",
                f"private module-level name {name!r} is assigned but "
                "never referenced",
            ))
        return findings
