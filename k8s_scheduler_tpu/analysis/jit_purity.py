"""JIT-PURITY (JP0xx): effect leaks into traced regions + compile-churn.

Stands on the interprocedural effect engine (analysis/effects.py): the
*traced region* is every function reachable from a jit/vmap entry
point, and each in-region function is checked for the host effects its
own frame performs. TRACE-SAFETY already owns time/RNG/print/import/
global under trace (TS001-TS003); this pass covers the effect kinds a
per-file matcher cannot see are traced, plus two compile-cache-churn
hazards that defeat the arena's program cache:

- JP001  host I/O (file/os/socket/subprocess/logging) reachable in a
         traced region: runs at trace time only, then never again —
         the compiled program silently stops doing it
- JP002  lock acquired inside a traced region: trace-time-only mutual
         exclusion is a no-op on replay (and a deadlock seed if the
         trace happens under the same lock)
- JP003  journal append / metric emit inside a traced region: records
         written once at trace time read as live progress (silent
         staleness — the WAL and dashboards lie)
- JP004  object attribute written inside a traced region: Python-side
         state mutated at trace time only, then frozen (the compiled
         program replays without it); `__init__` of objects built
         during the trace is exempt
- JP005  non-deterministic jit discriminator argument (id()/hash()/
         clock/RNG/uuid/pid, or unsorted dict iteration): every run
         mints a fresh cache key, so the compile cache never hits
         (the `_fw_disc` sorted(...) contract in core/cycle.py)
- JP006  jit wrapper constructed inside a loop: each iteration builds
         a fresh callable with an empty compile cache — memoize the
         wrapper or hoist it out of the loop
"""

from __future__ import annotations

import ast

from .callgraph import FuncInfo, attribute_chain, own_body_nodes
from .core import Finding, LintContext
from .effects import (
    JIT_NAMES,
    EffectEngine,
    module_shim,
)
from .registry import PassBase

# effect kinds this pass reports, and how (TS002/TS003 own time/rng/
# global — double-flagging one line under two codes would force double
# suppressions)
_KIND_TO_CODE = {
    "io": "JP001",
    "lock": "JP002",
    "journal": "JP003",
    "metrics": "JP003",
    "self_write": "JP004",
}

_KIND_WHY = {
    "io": "host I/O runs at trace time only; the compiled program "
          "silently stops doing it on replay",
    "lock": "a trace-time lock acquisition is a no-op in the compiled "
            "program (and a deadlock seed if tracing happens under "
            "the same lock)",
    "journal": "a journal record appended at trace time is written "
               "once, then never again — acked work would look "
               "durable while the WAL goes stale",
    "metrics": "a metric emitted at trace time moves once, then "
               "freezes — dashboards read live progress that is not "
               "happening",
    "self_write": "an attribute written at trace time mutates Python "
                  "state once; the compiled program replays without "
                  "it",
}

_NONDET_CALLS = frozenset({"id", "hash"})
_DICT_ITER = frozenset({"items", "keys", "values"})


class JitPurityPass(PassBase):
    name = "JIT-PURITY"
    codes = {
        "JP001": "host I/O reachable inside a traced region",
        "JP002": "lock acquired inside a traced region",
        "JP003": "journal append / metric emit inside a traced region "
                 "(trace-time-only: silent staleness)",
        "JP004": "object attribute written inside a traced region",
        "JP005": "non-deterministic jit discriminator (defeats the "
                 "compile cache)",
        "JP006": "jit wrapper constructed inside a loop (fresh compile "
                 "cache per iteration)",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        engine: EffectEngine = ctx.effects
        index = ctx.index
        out: list[Finding] = []

        # JP001-JP004: direct effects of every in-region function; the
        # region is interprocedural, the attribution is the function's
        # own frame so the finding lands on the offending line
        region = engine.traced_region()
        for fid in sorted(region):
            f = index.funcs[fid]
            if f.name in ("__init__", "__post_init__"):
                # constructing a fresh object during the trace writes
                # self by definition; the hazard JP004 targets is
                # mutation of pre-existing state
                continue
            path = region[fid]
            via = " -> ".join(path)
            for e in engine.direct(fid):
                code = _KIND_TO_CODE.get(e.kind)
                if code is None or e.detail == "print":
                    continue  # time/rng/print/global are TS002/TS003
                out.append(Finding(
                    f.file.rel, e.line, code,
                    f"{e.detail} in traced-reachable {f.qualname} "
                    f"(traced via {via}): {_KIND_WHY[e.kind]}",
                ))

        # JP005/JP006: jit call-site shape checks, everywhere
        for f in self._all_frames(ctx):
            out.extend(self._check_frames(engine, f))
        return out

    def _all_frames(self, ctx: LintContext):
        index = ctx.index
        for fid in sorted(index.funcs):
            yield index.funcs[fid]
        for sf in index.files:
            yield module_shim(sf)

    # ---- JP005: discriminator determinism --------------------------------

    def _check_frames(
        self, engine: EffectEngine, f: FuncInfo
    ) -> list[Finding]:
        out: list[Finding] = []
        aliases = engine.aliases_for(f.file)
        loops = self._loop_lines(f)
        for node in own_body_nodes(f.node):
            if not isinstance(node, ast.Call):
                continue
            chain = attribute_chain(node.func)
            if not chain or chain[-1] not in JIT_NAMES:
                continue
            label = ".".join(chain)
            for arg in list(node.args[1:]) + [
                kw.value for kw in node.keywords
            ]:
                for line, why in self._nondet(arg, aliases, False):
                    out.append(Finding(
                        f.file.rel, line, "JP005",
                        f"non-deterministic {label}() discriminator in "
                        f"{f.qualname}: {why} — every run mints a "
                        "fresh compile-cache key, so the cache never "
                        "hits across runs (sort / use stable inputs, "
                        "like _fw_disc in core/cycle.py)",
                    ))
            if node.lineno in loops and node.args:
                out.append(Finding(
                    f.file.rel, node.lineno, "JP006",
                    f"{label}() constructed inside a loop in "
                    f"{f.qualname}: each iteration builds a fresh "
                    "callable with an empty compile cache "
                    "(re-trace + re-compile per iteration); hoist "
                    "the wrapper or memoize it keyed on the callee",
                ))
        return out

    def _loop_lines(self, f: FuncInfo) -> set[int]:
        """Line numbers inside a For/While body of f's own frame. Since
        the JP006 call check itself only looks at f's own frame, a jit
        call on one of these lines really does repeat per iteration
        (loops belonging to nested defs are not seen here — a nested
        def is its own frame)."""
        lines: set[int] = set()
        for node in own_body_nodes(f.node):
            if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                first = node.body[0].lineno
                last = node.body[-1].end_lineno or node.body[-1].lineno
                lines.update(range(first, last + 1))
        return lines

    def _nondet(
        self, expr: ast.AST, aliases: dict[str, str], in_sorted: bool
    ) -> list[tuple[int, str]]:
        """(line, reason) for every non-deterministic construct in a
        discriminator expression; `sorted(...)` neutralizes dict-order
        dependence below it (the core/cycle.py _fw_disc contract)."""
        out: list[tuple[int, str]] = []
        if isinstance(expr, ast.Call):
            chain = attribute_chain(expr.func)
            if chain:
                tag = aliases.get(chain[0])
                last = chain[-1]
                if chain == ("sorted",):
                    for a in expr.args:
                        out.extend(self._nondet(a, aliases, True))
                    return out
                if len(chain) == 1 and last in _NONDET_CALLS:
                    out.append((expr.lineno,
                                f"{last}() is process-random (ASLR / "
                                "PYTHONHASHSEED)"))
                elif tag in ("time", "datetime") or (
                    tag and tag.startswith("time.")
                ):
                    out.append((expr.lineno, "clock read"))
                elif tag == "random" or (
                    tag and tag.startswith("random.")
                ):
                    out.append((expr.lineno, "host RNG"))
                elif tag == "uuid" or chain[0] == "uuid":
                    out.append((expr.lineno, "uuid mint"))
                elif tag == "os" and last in ("getpid", "urandom"):
                    out.append((expr.lineno, f"os.{last}()"))
                elif last in _DICT_ITER and not in_sorted:
                    out.append((expr.lineno,
                                f".{last}() iterates in container "
                                "order; wrap in sorted(...)"))
            for a in list(expr.args) + [kw.value for kw in expr.keywords]:
                out.extend(self._nondet(a, aliases, in_sorted))
            return out
        if isinstance(expr, (ast.Set, ast.SetComp)) and not in_sorted:
            out.append((expr.lineno,
                        "set iteration order is hash-random"))
        for child in ast.iter_child_nodes(expr):
            if not isinstance(child, (ast.Lambda, ast.FunctionDef)):
                out.extend(self._nondet(child, aliases, in_sorted))
        return out
