"""Lightweight intra-repo code index + call graph for schedlint passes.

Pure-AST, no imports of the analyzed code. Precision model (documented
so pass authors know what they're standing on):

- Every function, method, nested function, and lambda is indexed with a
  stable id `"<rel path>::<qualname>"`.
- A function's *references* are every Name load and attribute chain in
  its own body (nested function bodies belong to the nested function,
  but their default args and decorators evaluate in the enclosing
  scope and are credited there).
- Resolution is name-based and deliberately OVER-approximate for
  reachability (a static-safety walk must not miss an edge):
    * bare names resolve through the lexical scope chain (own nested
      defs -> enclosing functions -> module functions -> `from X
      import f` aliases);
    * dotted chains rooted at an import alias resolve exactly into the
      target module;
    * `self.m` / `cls.m` resolves through the enclosing class and its
      by-name base chain;
    * anything else falls back to "every indexed function named m",
      EXCEPT names in _GENERIC_ATTRS (list.append, dict.get, ...),
      which would connect the graph through builtin-container noise.
- Functions merely *referenced* (passed as callbacks to lax.scan /
  lax.cond / Thread(target=...)) count as called — that is the point.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

from .core import SourceFile

# attribute names whose by-name fallback would wire the graph through
# builtin containers / file objects / locks rather than real calls
_GENERIC_ATTRS = frozenset({
    "append", "add", "get", "pop", "update", "clear", "copy", "items",
    "keys", "values", "extend", "insert", "remove", "sort", "split",
    "join", "strip", "read", "write", "open", "close", "flush", "set",
    "inc", "observe", "start", "commit", "note", "mark", "wait",
    "notify", "notify_all", "release", "acquire", "put", "encode",
    "decode", "dump", "dumps", "load", "loads", "run", "stop", "send",
    "main", "setdefault", "discard", "count", "index", "format",
    "replace", "lower", "upper", "popitem", "move_to_end", "group",
    "match", "search", "findall", "pack", "unpack", "unpack_from",
})


@dataclasses.dataclass
class FuncInfo:
    id: str
    file: SourceFile
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    name: str  # "<lambda>" for lambdas
    qualname: str
    cls: str | None  # enclosing class name (methods only)
    parent: str | None  # enclosing function id (nested defs/lambdas)
    lineno: int

    @property
    def module(self) -> str:
        return self.file.module


@dataclasses.dataclass
class ClassInfo:
    module: str
    name: str
    bases: list[str]  # base names (last attribute component)
    methods: dict[str, str]  # method name -> func id
    lineno: int


def attribute_chain(node: ast.AST) -> tuple[str, ...] | None:
    """('a','b','c') for `a.b.c` when rooted at a Name, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def own_body_nodes(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Every AST node that executes in `fn_node`'s own frame — nested
    function/lambda bodies are excluded (they are indexed separately),
    but their decorators and default-argument expressions, which
    evaluate in THIS frame, are included."""
    if isinstance(fn_node, ast.Lambda):
        stack: list[ast.AST] = [fn_node.body]
    else:
        stack = list(fn_node.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            stack.extend(node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
            continue
        if isinstance(node, ast.Lambda):
            stack.extend(node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
            continue
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _Indexer(ast.NodeVisitor):
    def __init__(self, index: "CodeIndex", sf: SourceFile) -> None:
        self.index = index
        self.sf = sf
        self.scope: list[str] = []  # qualname parts
        self.cls_stack: list[ClassInfo] = []
        self.fn_stack: list[FuncInfo] = []
        self.lambda_counter = 0

    def _add_func(self, node, name: str) -> FuncInfo:
        qual = ".".join(self.scope + [name])
        info = FuncInfo(
            id=f"{self.sf.rel}::{qual}",
            file=self.sf,
            node=node,
            name=name,
            qualname=qual,
            cls=self.cls_stack[-1].name
            if self.cls_stack and self.scope
            and self.scope[-1] == self.cls_stack[-1].name else None,
            parent=self.fn_stack[-1].id if self.fn_stack else None,
            lineno=node.lineno,
        )
        self.index._register_func(info)
        return info

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            chain = attribute_chain(b)
            if chain:
                bases.append(chain[-1])
        ci = ClassInfo(
            module=self.sf.module, name=node.name, bases=bases,
            methods={}, lineno=node.lineno,
        )
        self.index._register_class(ci)
        self.cls_stack.append(ci)
        self.scope.append(node.name)
        self.generic_visit(node)
        self.scope.pop()
        self.cls_stack.pop()

    def _visit_func(self, node) -> None:
        info = self._add_func(node, node.name)
        if (
            self.cls_stack and self.scope
            and self.scope[-1] == self.cls_stack[-1].name
        ):
            self.cls_stack[-1].methods[node.name] = info.id
        self.scope.append(node.name)
        self.fn_stack.append(info)
        self.generic_visit(node)
        self.fn_stack.pop()
        self.scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.lambda_counter += 1
        self._add_func(node, f"<lambda-{self.lambda_counter}>")
        self.generic_visit(node)


class CodeIndex:
    def __init__(self, files: list[SourceFile]) -> None:
        self.files = files
        self.funcs: dict[str, FuncInfo] = {}
        self.classes: dict[tuple[str, str], ClassInfo] = {}
        # module -> {module-level function name -> id}
        self.module_funcs: dict[str, dict[str, str]] = {}
        # function name -> ids (the by-name fallback table)
        self.by_name: dict[str, set[str]] = {}
        # (rel, lineno, col) -> func id, for node -> info lookups
        self._by_pos: dict[tuple[str, int, int], str] = {}
        # parent func id -> {nested def name -> id} (lexical scope table)
        self._children: dict[str, dict[str, str]] = {}
        # per-file import alias tables
        self._aliases: dict[str, dict[str, tuple[str, str | None]]] = {}
        self._modules = set()
        for sf in files:
            self._modules.add(sf.module)
        for sf in files:
            _Indexer(self, sf).visit(sf.tree)
            self._aliases[sf.rel] = self._collect_aliases(sf)
        self._resolved: dict[str, frozenset[str]] = {}

    # ---- construction ----------------------------------------------------

    def _register_func(self, info: FuncInfo) -> None:
        self.funcs[info.id] = info
        self._by_pos[
            (info.file.rel, info.node.lineno, info.node.col_offset)
        ] = info.id
        if info.parent is None and info.cls is None:
            self.module_funcs.setdefault(info.module, {})[info.name] = info.id
        if info.parent is not None:
            self._children.setdefault(info.parent, {})[info.name] = info.id
        if not info.name.startswith("<lambda"):
            self.by_name.setdefault(info.name, set()).add(info.id)

    def _register_class(self, ci: ClassInfo) -> None:
        self.classes[(ci.module, ci.name)] = ci

    def _collect_aliases(
        self, sf: SourceFile
    ) -> dict[str, tuple[str, str | None]]:
        """alias -> (module, symbol|None). symbol None = the alias IS a
        module; otherwise it is `symbol` inside `module`. Only aliases
        that resolve into the indexed file set are kept."""
        out: dict[str, tuple[str, str | None]] = {}
        for node in sf.walk():
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name in self._modules:
                        out[a.asname or a.name.split(".")[0]] = (
                            a.name if a.asname else a.name.split(".")[0],
                            None,
                        )
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from(sf, node)
                if base is None:
                    continue
                for a in node.names:
                    target = f"{base}.{a.name}" if base else a.name
                    bound = a.asname or a.name
                    if target in self._modules:
                        out[bound] = (target, None)
                    elif base in self._modules:
                        out[bound] = (base, a.name)
        return out

    def _resolve_from(
        self, sf: SourceFile, node: ast.ImportFrom
    ) -> str | None:
        """Absolute dotted module for a `from ... import` statement."""
        if node.level == 0:
            return node.module
        pkg = sf.module.split(".")
        if not sf.rel.endswith("__init__.py"):
            pkg = pkg[:-1]
        drop = node.level - 1
        if drop > len(pkg):
            return None
        base = pkg[: len(pkg) - drop] if drop else pkg
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    # ---- lookups ---------------------------------------------------------

    def func_at(self, rel: str, node: ast.AST) -> FuncInfo | None:
        fid = self._by_pos.get((rel, node.lineno, node.col_offset))
        return self.funcs.get(fid) if fid else None

    def subclasses_of(self, *base_names: str) -> list[ClassInfo]:
        """Classes deriving (transitively, by base NAME) from any of the
        given names — including name-only matches across modules."""
        want = set(base_names)
        changed = True
        while changed:
            changed = False
            for ci in self.classes.values():
                if ci.name in want:
                    continue
                if any(b in want for b in ci.bases):
                    want.add(ci.name)
                    changed = True
        return [
            ci for ci in self.classes.values()
            if ci.name in want and ci.name not in base_names
        ] + [ci for ci in self.classes.values() if ci.name in base_names]

    def class_method(
        self, module: str, cls_name: str, method: str
    ) -> set[str]:
        """Resolve a method through the class + its by-name base chain."""
        seen: set[str] = set()
        queue = [(module, cls_name)]
        while queue:
            key = queue.pop()
            if key in seen:
                continue
            seen.add(key)
            ci = self.classes.get(key)
            if ci is None:
                # base defined in another module: match by name anywhere
                cands = [
                    c for c in self.classes.values() if c.name == key[1]
                ]
                if not cands:
                    continue
                for c in cands:
                    queue.append((c.module, c.name))
                continue
            if method in ci.methods:
                return {ci.methods[method]}
            for b in ci.bases:
                queue.append((ci.module, b))
        return set()

    # ---- reference resolution --------------------------------------------

    def resolve_name(self, f: FuncInfo, name: str) -> set[str]:
        """Bare-name reference from inside `f`."""
        # lexical chain: own + enclosing functions' direct nested defs
        cur: FuncInfo | None = f
        while cur is not None:
            hit = self._children.get(cur.id, {}).get(name)
            if hit is not None:
                return {hit}
            cur = self.funcs.get(cur.parent) if cur.parent else None
        mod = self.module_funcs.get(f.module, {})
        if name in mod:
            return {mod[name]}
        alias = self._aliases.get(f.file.rel, {}).get(name)
        if alias:
            amod, sym = alias
            if sym is not None:
                target = self.module_funcs.get(amod, {}).get(sym)
                if target:
                    return {target}
                # imported class: its __init__ runs
                return self.class_method(amod, sym, "__init__")
        return set()

    def resolve_chain(
        self, f: FuncInfo, chain: tuple[str, ...]
    ) -> set[str]:
        """Dotted-chain reference from inside `f` (see module docstring
        for the precision ladder)."""
        if len(chain) == 1:
            return self.resolve_name(f, chain[0])
        head, rest = chain[0], chain[1:]
        alias = self._aliases.get(f.file.rel, {}).get(head)
        if alias and alias[1] is None:
            # module alias: walk submodule components exactly
            mod = alias[0]
            i = 0
            while i < len(rest) - 1 and f"{mod}.{rest[i]}" in self._modules:
                mod = f"{mod}.{rest[i]}"
                i += 1
            name = rest[i]
            target = self.module_funcs.get(mod, {}).get(name)
            if target:
                out = {target}
            else:
                out = self.class_method(mod, name, "__init__")
            # Plugin().method(...) style chains keep resolving by name
            for extra in rest[i + 1:]:
                out |= self._fallback(extra)
            return out
        if head in ("self", "cls") and f.cls is not None:
            hit = self.class_method(f.module, f.cls, rest[0])
            if hit:
                return hit
        return self._fallback(chain[-1])

    def _fallback(self, name: str) -> set[str]:
        if name in _GENERIC_ATTRS:
            return set()
        return set(self.by_name.get(name, ()))

    def references(self, f: FuncInfo) -> frozenset[str]:
        """Every function id referenced from `f`'s own frame (memoized)."""
        hit = self._resolved.get(f.id)
        if hit is not None:
            return hit
        out: set[str] = set()
        for node in own_body_nodes(f.node):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load
            ):
                out |= self.resolve_name(f, node.id)
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                chain = attribute_chain(node)
                if chain is not None:
                    out |= self.resolve_chain(f, chain)
                else:
                    out |= self._fallback(node.attr)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pass
        # nested defs referenced by Name load are covered above; a nested
        # Lambda expression is a reference by construction (it is built,
        # and virtually always invoked, where it appears)
        for name, fid in self._children.get(f.id, {}).items():
            if name.startswith("<lambda"):
                out.add(fid)
        result = frozenset(out - {f.id})
        self._resolved[f.id] = result
        return result

    def resolve_callback(self, f: FuncInfo, target) -> set[str]:
        """Resolve a callback EXPRESSION (a jit first-arg, a
        Thread(target=...), an observer/scrape registration) to function
        ids: bare names through the scope chain, lambdas by position,
        dotted chains through the precision ladder, functools.partial by
        unwrapping its first argument. One ladder shared by every
        root-discovery consumer so their resolution cannot drift."""
        if target is None:
            return set()
        if isinstance(target, ast.Name):
            return self.resolve_name(f, target.id)
        if isinstance(target, ast.Lambda):
            info = self.func_at(f.file.rel, target)
            return {info.id} if info is not None else set()
        if isinstance(target, ast.Attribute):
            chain = attribute_chain(target)
            if chain is not None:
                return self.resolve_chain(f, chain)
            return set()
        if isinstance(target, ast.Call):
            fchain = attribute_chain(target.func)
            if fchain and fchain[-1] == "partial" and target.args:
                return self.resolve_callback(f, target.args[0])
        return set()

    def reachable(self, roots: Iterable[str]) -> set[str]:
        seen: set[str] = set()
        stack = [r for r in roots if r in self.funcs]
        while stack:
            fid = stack.pop()
            if fid in seen:
                continue
            seen.add(fid)
            for ref in self.references(self.funcs[fid]):
                if ref not in seen:
                    stack.append(ref)
        return seen
