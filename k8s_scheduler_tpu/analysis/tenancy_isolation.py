"""TENANCY-ISOLATION (TN0xx): the cross-tenant state boundary.

tenancy/registry.py keeps every per-tenant container — nodes, pending
and bound pods, the per-tenant encoder and its arena buffers — behind
`_tn_`-prefixed attributes, and tests/test_tenancy.py proves
dynamically that no tenant's decisions depend on another's state (the
packed N-tenant run is bit-equal per tenant to N sequential runs).
That property only holds while nothing OUTSIDE the tenancy package
reaches into a tenant's slices: a core/framework/service code path
reading another tenant's arena row or queue would be invisible to the
equivalence suite the day its inputs happen to match, and a capacity
or affinity leak the day they don't.

This pass pins the boundary statically: any `_tn_*` attribute access
(read or write) in a module outside `k8s_scheduler_tpu/tenancy/` is a
finding. Name-based and deliberately over-approximate, like the
sibling passes — the prefix is the contract, so the fix is to go
through TenantRegistry's public API (or to move the code into
tenancy/), never to rename the attribute.

- TN001  `_tn_*` tenant-state attribute accessed outside tenancy/
"""

from __future__ import annotations

import ast

from .core import Finding, LintContext
from .registry import PassBase

_TENANCY_PREFIX = "k8s_scheduler_tpu/tenancy/"


class TenancyIsolationPass(PassBase):
    name = "TENANCY-ISOLATION"
    codes = {
        "TN001": (
            "per-tenant state (_tn_* attribute) accessed outside "
            "the tenancy package"
        ),
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        findings: list[Finding] = []
        for sf in ctx.files:
            if sf.rel.startswith(_TENANCY_PREFIX):
                continue
            for node in sf.walk():
                if not isinstance(node, ast.Attribute):
                    continue
                if not node.attr.startswith("_tn_"):
                    continue
                findings.append(Finding(
                    sf.rel, node.lineno, "TN001",
                    f"access to tenant-private attribute "
                    f"{node.attr!r} outside tenancy/ crosses the "
                    "virtual-cluster isolation boundary (the "
                    "bit-equality property tests/test_tenancy.py "
                    "checks dynamically): go through the "
                    "TenantRegistry API instead",
                ))
        return findings
