"""Analysis-pass registry: name -> factory, the same plugin aesthetic as
framework/registry.py — out-of-tree passes register exactly like the
defaults, and scripts/schedlint.py drives whatever is registered."""

from __future__ import annotations

from typing import Callable

from .core import Finding, LintContext


class PassBase:
    """A schedlint pass. Subclasses set `name` (the registry key, also
    the ISSUE-facing pass name like "TRACE-SAFETY"), `codes` (code ->
    one-line description, the documentation surface README renders), and
    implement `run`."""

    name: str = ""
    codes: dict[str, str] = {}

    def __init__(self, args: dict | None = None):
        self.args = args or {}

    def run(self, ctx: LintContext) -> list[Finding]:
        raise NotImplementedError


Factory = Callable[[dict], PassBase]


class PassRegistry:
    def __init__(self) -> None:
        self._factories: dict[str, Factory] = {}

    def register(self, name: str, factory: Factory) -> None:
        if name in self._factories:
            raise ValueError(f"pass {name!r} already registered")
        self._factories[name] = factory

    def make(self, name: str, args: dict | None = None) -> PassBase:
        if name not in self._factories:
            raise KeyError(f"unknown pass {name!r}; registered: "
                           f"{sorted(self._factories)}")
        return self._factories[name](args or {})

    def names(self) -> list[str]:
        return sorted(self._factories)


def default_registry() -> PassRegistry:
    from .durability_order import DurabilityOrderPass
    from .hygiene import HygienePass
    from .inventory import InventoryDriftPass
    from .jit_purity import JitPurityPass
    from .journal_emit import JournalEmitOncePass
    from .lock_discipline import LockDisciplinePass
    from .races import RacesPass
    from .robustness import RobustnessPass
    from .shard_safety import ShardSafetyPass
    from .tenancy_isolation import TenancyIsolationPass
    from .threads import ThreadsPass
    from .trace_safety import TraceSafetyPass

    r = PassRegistry()
    for cls in (
        TraceSafetyPass,
        JitPurityPass,
        LockDisciplinePass,
        JournalEmitOncePass,
        DurabilityOrderPass,
        InventoryDriftPass,
        HygienePass,
        RobustnessPass,
        ThreadsPass,
        RacesPass,
        ShardSafetyPass,
        TenancyIsolationPass,
    ):
        r.register(cls.name, lambda args, _cls=cls: _cls(args))
    return r


def all_codes(registry: PassRegistry | None = None) -> dict[str, str]:
    """code -> description across every registered pass (the README
    table's source of truth). Raises when two passes claim the same
    code — last-write-wins here would silently document one pass's
    description for another pass's findings, and suppressions/baseline
    entries keyed on the code would hit both."""
    registry = registry or default_registry()
    out: dict[str, str] = {}
    owner: dict[str, str] = {}
    for name in registry.names():
        for code, desc in registry.make(name).codes.items():
            if code in owner:
                raise ValueError(
                    f"finding code {code!r} claimed by both "
                    f"{owner[code]!r} and {name!r}; codes must be "
                    "unique across passes"
                )
            owner[code] = name
            out[code] = desc
    return out
