"""SHARD-SAFETY (SH0xx): the PR 9 shard-exactness rules, machine-pinned.

PR 9 made multi-chip serving bit-identical to single-device at every
device count by (a) routing every claim-path reduce through the
shard-invariant selection primitives in ops/argsel.py (`jnp.argmax` /
`lax.top_k` merge equal-valued entries in shard-local order under
GSPMD), (b) eliminating the axis-0 `jnp.concatenate` of pods-sharded
1-D vectors that this jaxlib miscompiles under SPMD (root-caused in
AUDIT_SHARDED_r05; guarded until now only by one repro test), and
(c) centralizing every "which PartitionSpec does this array get" rule
in `parallel/mesh.mesh_pin`. ROADMAP item 3 (multi-host mesh) rewrites
exactly these surfaces — this pass is the static guardrail that must
hold while it does.

Scope: SH001/SH002 walk the call graph from the mesh-built program
roots — functions named `build_carry_fns`, `rounds_commit`, or
`_constrain_carry` (the carry-cycle builder, the rounds engine entry,
and the carry sharding constraint; everything that can ever trace under
a mesh is reachable from these). SH003 scans the WHOLE tree: a
PartitionSpec built anywhere outside parallel/mesh.py is a second copy
of the sharding rule waiting to drift.

- SH001  raw `jnp.argmax`/`jnp.argmin`/`*.top_k` in mesh-reachable
         code: use ops/argsel.argmax_first / top_k_first (shard-
         invariant tie order). Reduces over axes that can never be
         mesh-sharded (inner pad axes like MPN+1) are inventoried with
         `# schedlint: disable=SH001 -- why`.
- SH002  axis-0 (or default-axis) `jnp.concatenate` in mesh-reachable
         code: the PR 9 jaxlib SPMD miscompile class — concatenating
         pods-sharded 1-D operands produced wrong values under GSPMD.
         Use stack+reshape (ops/rounds.py's fix) or inventory
         replicated-operand sites.
- SH003  `PartitionSpec` / `NamedSharding` constructed outside
         parallel/mesh.py: the sharding rule lives in `mesh_pin` (and
         `shard_snapshot`) ONLY — a spec built elsewhere can disagree
         with the carry tables' layout and silently resharded-copy
         every dispatch.

Like the rest of the framework the walk is over-approximate: a
function referenced from a mesh root (lax.scan/cond bodies, plugin
hooks passed through the rounds engine) counts as called.
"""

from __future__ import annotations

import ast

from .callgraph import attribute_chain, own_body_nodes
from .core import Finding, LintContext, SourceFile
from .registry import PassBase
from .trace_safety import _ALIAS_TARGETS, _module_aliases

# the mesh-built program roots (see module docstring)
MESH_ROOT_FUNCTIONS = frozenset({
    "build_carry_fns", "rounds_commit", "_constrain_carry",
})

# the sharding-layout module that OWNS PartitionSpec construction
_MESH_MODULE_SUFFIX = "parallel/mesh.py"

_RAW_REDUCES = frozenset({"argmax", "argmin"})


def _is_axis0(call: ast.Call) -> bool:
    """True when a concatenate call can run on axis 0: explicitly, by
    default, via a NEGATIVE axis (for the 1-D operands that define the
    miscompile class, axis=-1 IS axis 0 — rank is not statically
    knowable, so negatives count as dangerous), or via a dynamic axis
    expression (same conservatism)."""
    axis = None
    for kw in call.keywords:
        if kw.arg == "axis":
            axis = kw.value
    if axis is None and len(call.args) >= 2:
        axis = call.args[1]
    if axis is None:
        return True  # default axis=0
    if isinstance(axis, ast.Constant) and isinstance(axis.value, int):
        return axis.value <= 0
    if isinstance(axis, ast.UnaryOp) and isinstance(axis.op, ast.USub):
        return True  # -1 parses as USub(Constant(1))
    return True  # dynamic axis: assume the dangerous one


class ShardSafetyPass(PassBase):
    name = "SHARD-SAFETY"
    codes = {
        "SH001": "raw argmax/top_k reduce in mesh-reachable code "
                 "(shard-local tie order; use ops/argsel)",
        "SH002": "axis-0 jnp.concatenate in mesh-reachable code "
                 "(the PR 9 jaxlib SPMD miscompile class)",
        "SH003": "PartitionSpec/NamedSharding built outside "
                 "parallel/mesh.py (mesh_pin owns the sharding rule)",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        index = ctx.index
        roots = {
            fid for fid, f in index.funcs.items()
            if f.name in MESH_ROOT_FUNCTIONS
        }
        reachable = index.reachable(roots)
        # aliases once per FILE, not per reachable function — a file
        # like ops/rounds.py holds dozens of mesh-reachable nested fns
        self._aliases: dict[str, dict] = {}
        findings: list[Finding] = []
        for fid in sorted(reachable):
            f = index.funcs[fid]
            findings.extend(self._check_reachable(f))
        for sf in ctx.files:
            findings.extend(self._check_spec_construction(sf))
        return findings

    # ---- SH001 / SH002 (mesh-reachable only) -----------------------------

    def _check_reachable(self, f) -> list[Finding]:
        sf = f.file
        aliases = self._aliases.get(sf.rel)
        if aliases is None:
            aliases = self._aliases[sf.rel] = _module_aliases(
                sf, _ALIAS_TARGETS
            )
        out: list[Finding] = []
        for node in own_body_nodes(f.node):
            if not isinstance(node, ast.Call):
                continue
            chain = attribute_chain(node.func)
            if chain is None:
                continue
            tag = aliases.get(chain[0]) if len(chain) > 1 else None
            if (
                tag == "jnp" and len(chain) == 2
                and chain[1] in _RAW_REDUCES
            ):
                out.append(Finding(
                    sf.rel, node.lineno, "SH001",
                    f"jnp.{chain[1]} in mesh-reachable {f.qualname}: "
                    "ties merge in shard-local order under GSPMD, so "
                    "placements diverge across device counts — use "
                    "ops/argsel.argmax_first (or inventory a reduce "
                    "over a never-sharded axis)",
                ))
            elif chain[-1] == "top_k":
                out.append(Finding(
                    sf.rel, node.lineno, "SH001",
                    f"top_k in mesh-reachable {f.qualname}: the "
                    "partitioned (value, index) combiner's tie order "
                    "is implementation-defined — use "
                    "ops/argsel.top_k_first (total-order 2-key sort)",
                ))
            elif (
                tag == "jnp" and len(chain) == 2
                and chain[1] == "concatenate"
                and _is_axis0(node)
            ):
                out.append(Finding(
                    sf.rel, node.lineno, "SH002",
                    f"axis-0 jnp.concatenate in mesh-reachable "
                    f"{f.qualname}: this jaxlib miscompiles axis-0 "
                    "concatenation of sharded 1-D operands under SPMD "
                    "(the PR 9 root cause) — use stack+reshape, or "
                    "inventory a provably-replicated site",
                ))
        return out

    # ---- SH003 (whole tree) ----------------------------------------------

    def _check_spec_construction(self, sf: SourceFile) -> list[Finding]:
        if sf.rel.endswith(_MESH_MODULE_SUFFIX):
            return []
        out: list[Finding] = []
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            chain = attribute_chain(node.func)
            if chain and chain[-1] in ("PartitionSpec", "NamedSharding"):
                out.append(Finding(
                    sf.rel, node.lineno, "SH003",
                    f"{chain[-1]} constructed outside parallel/mesh.py: "
                    "the which-spec-does-this-array-get rule lives in "
                    "mesh.mesh_pin/shard_snapshot only — route through "
                    "them (or inventory plumbing like shard_map "
                    "in_specs with a justification)",
                ))
        return out
