"""TRACE-SAFETY (TS0xx): impure Python reachable from jitted programs.

PR 1's worst bug was a lazy `from ..ops import preemption` INSIDE the
traced post_filter: the module's top-level `jnp` constants were created
under the active trace, and a later retrace read them as escaped
tracers of a dead trace (UnexpectedTracerError, ~25 tests down). This
pass walks the call graph from every jit entry point — the first
argument of `_jit(...)`/`jax.jit(...)` calls, plus every compute hook
of `PluginBase` subclasses (the plugin kernels are traced by
definition) — and flags Python that must not run under a trace:

- TS001  import statement inside a traced-reachable function (the PR 1
         class; the message escalates when the imported module holds
         module-level jnp constants)
- TS002  host-impure call under trace: time.*, datetime.now/utcnow/
         today/fromtimestamp, random.*, numpy.random.*, print
- TS003  `global` declaration (module-state mutation) under trace
- TS004  jnp.array/asarray over a Python literal list/tuple under trace
         (a fresh device constant re-materialized per trace; hoist it
         to module scope)

The walk is deliberately over-approximate (see analysis/callgraph.py):
a function passed as a callback (lax.scan/cond bodies, plugin hooks
dispatched through the Framework) counts as called.
"""

from __future__ import annotations

import ast

from .callgraph import CodeIndex, FuncInfo, attribute_chain, own_body_nodes
from .core import Finding, LintContext, SourceFile
from .effects import (
    ALIAS_TARGETS as _ALIAS_TARGETS,
    module_aliases as _module_aliases,
    traced_roots,
)
from .registry import PassBase

_DATETIME_IMPURE = frozenset({"now", "utcnow", "today", "fromtimestamp"})


def module_jnp_constants(sf: SourceFile) -> list[int]:
    """Lines of module-level assignments whose value calls into jnp —
    the constants that make a lazy import of this module trace-fatal."""
    aliases = _module_aliases(sf, _ALIAS_TARGETS)
    out = []
    for stmt in sf.tree.body:
        if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            continue
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                chain = attribute_chain(node.func)
                if chain and aliases.get(chain[0]) == "jnp":
                    out.append(stmt.lineno)
                    break
    return out


def _is_literal_array(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literal_array(e) for e in node.elts)
    return isinstance(node, ast.Constant)


class TraceSafetyPass(PassBase):
    name = "TRACE-SAFETY"
    codes = {
        "TS001": "import executed inside a jit-traced function",
        "TS002": "host-impure call (time/datetime/random/print) under "
                 "trace",
        "TS003": "global-state mutation declared under trace",
        "TS004": "jnp constant built from a Python literal under trace",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        index = ctx.index
        # root discovery lives in effects.py (one ladder shared with
        # the JIT-PURITY engine, so the two cannot drift)
        roots = set(traced_roots(index))
        reachable = index.reachable(roots)
        findings: list[Finding] = []
        for fid in sorted(reachable):
            f = index.funcs[fid]
            findings.extend(self._check_function(ctx, index, f))
        return findings

    # ---- per-function checks ---------------------------------------------

    def _check_function(
        self, ctx: LintContext, index: CodeIndex, f: FuncInfo
    ) -> list[Finding]:
        sf = f.file
        aliases = _module_aliases(sf, _ALIAS_TARGETS)
        label = f.qualname
        out: list[Finding] = []

        def emit(code: str, line: int, msg: str) -> None:
            out.append(Finding(sf.rel, line, code, msg))

        for node in own_body_nodes(f.node):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                out.extend(self._import_finding(ctx, index, f, node))
            elif isinstance(node, ast.Global):
                emit(
                    "TS003", node.lineno,
                    f"`global {', '.join(node.names)}` in traced "
                    f"function {label}: module state mutated under "
                    "trace is trace-order-dependent",
                )
            elif isinstance(node, ast.Call):
                chain = attribute_chain(node.func)
                if chain is None:
                    continue
                tag = aliases.get(chain[0])
                if chain == ("print",):
                    emit(
                        "TS002", node.lineno,
                        f"print() in traced function {label}: runs at "
                        "trace time only (use jax.debug.print)",
                    )
                elif tag == "time" and len(chain) > 1:
                    emit(
                        "TS002", node.lineno,
                        f"time.{chain[-1]}() in traced function "
                        f"{label}: clock reads freeze into the compiled "
                        "program as trace-time constants",
                    )
                elif tag and tag.startswith("time.") and len(chain) == 1:
                    emit(
                        "TS002", node.lineno,
                        f"{tag}() in traced function {label}: clock "
                        "reads freeze into the compiled program",
                    )
                elif tag == "datetime" and chain[-1] in _DATETIME_IMPURE:
                    emit(
                        "TS002", node.lineno,
                        f"datetime {chain[-1]}() in traced function "
                        f"{label}: wall-clock under trace",
                    )
                elif tag == "random" and len(chain) > 1:
                    emit(
                        "TS002", node.lineno,
                        f"random.{chain[-1]}() in traced function "
                        f"{label}: host RNG under trace (use jax.random "
                        "with an explicit key)",
                    )
                elif tag and tag.startswith("random.") and len(chain) == 1:
                    emit(
                        "TS002", node.lineno,
                        f"{tag}() in traced function {label}: host RNG "
                        "under trace (use jax.random)",
                    )
                elif (
                    tag == "np" and len(chain) >= 3
                    and chain[1] == "random"
                ):
                    emit(
                        "TS002", node.lineno,
                        f"numpy.random.{chain[-1]}() in traced function "
                        f"{label}: host RNG under trace",
                    )
                elif (
                    tag == "jnp" and len(chain) == 2
                    and chain[1] in ("array", "asarray")
                    and node.args and _is_literal_array(node.args[0])
                ):
                    emit(
                        "TS004", node.lineno,
                        f"jnp.{chain[1]}(<literal>) in traced function "
                        f"{label}: hoist the constant to module scope",
                    )
        return out

    def _import_finding(
        self, ctx: LintContext, index: CodeIndex, f: FuncInfo,
        node: ast.Import | ast.ImportFrom,
    ) -> list[Finding]:
        sf = f.file
        if isinstance(node, ast.Import):
            targets = [a.name for a in node.names]
            shown = ", ".join(targets)
        else:
            base = index._resolve_from(sf, node) or (node.module or "")
            targets = []
            for a in node.names:
                cand = f"{base}.{a.name}" if base else a.name
                if ctx.module(cand) is not None:
                    targets.append(cand)
                elif base:
                    targets.append(base)
            shown = f"{'.' * node.level}{node.module or ''} import " + \
                ", ".join(a.name for a in node.names)
        extra = ""
        for t in targets:
            target_sf = ctx.module(t)
            if target_sf is not None and module_jnp_constants(target_sf):
                extra = (
                    f" — {t} holds module-level jnp constants, which "
                    "would be created under the active trace and read "
                    "as escaped tracers on retrace (the PR 1 "
                    "UnexpectedTracerError class)"
                )
                break
        return [Finding(
            sf.rel, node.lineno, "TS001",
            f"import inside traced function {f.qualname} (from {shown})"
            ": a first import under trace runs arbitrary module-level "
            f"code inside the jit{extra}; import at module scope",
        )]
