"""INVENTORY-DRIFT (ID0xx): code <-> documentation surface cross-checks.

The generalization of scripts/lint_metrics.py (now a shim over this
pass): dashboards, runbooks, and the README are built from inventories
that silently rot when code moves. Three inventories are checked, each
in BOTH directions:

- ID001  metric families registered on SchedulerMetrics vs the
         metrics/metrics.py docstring and the README "## Observability"
         table, plus the REQUIRED_FAMILIES floor (the durable-state /
         leader families operations depends on)
- ID002  SchedulerConfiguration fields vs the camelCase YAML keys
         load_config() reads (a field without a key is dead config; a
         key without a field is a silent no-op in every user's YAML)
- ID003  cmd/main.py: `config.X` attribute writes must name real
         SchedulerConfiguration fields, `args.Y` reads must name real
         argparse flags (a typo'd override silently keeps the default)
- ID004  every YAML config key and every CLI flag is mentioned
         somewhere in README.md (the operator-facing surface)
- ID005  the cycle-phase inventory: every phase name in
         core/observe.PHASES must appear in the flight recorder's
         chrome-trace lane mapping (TRACE_LANE_FOR_PHASE, and vice
         versa), in the metrics/metrics.py docstring entry for
         scheduler_cycle_phase_seconds, and in the README
         "## Observability" section — the recorder, the metrics, and
         the trace export cannot disagree about what a phase is
- ID006  the compile-cache key inventory: the dimension names of
         models/packing.SIGNATURE_DIMS must equal
         core/compile_cache.SIG_KEY_FIELDS (a new pad dimension added
         without a cache-key field silently ALIASES distinct programs
         into one persistent-cache entry; a stale key field caches
         against a dimension that no longer exists), and every field of
         SIG_KEY_FIELDS + EXTRA_KEY_FIELDS must appear in the README
         "## Compile-regime management" key table
- ID007  the degradation-rung inventory: every rung name in
         core/degrade.RUNGS must appear in the README "## Failure
         model & degradation ladder" rung table (operators act on the
         rung names /healthz and the transition events carry; a rung
         added or renamed without its README row leaves the runbook
         pointing at modes that no longer exist)
- ID008  the sharded-collective budget inventory: every budget class
         in parallel/audit.COLLECTIVE_BUDGETS (the committed allowlist
         scripts/audit_sharded.py gates on) and every mesh-axis name
         in parallel/mesh.MESH_AXES must appear in the README
         "## Multi-chip and multi-host" budget table — a class or axis
         renamed without its doc row silently un-classifies the very
         collectives the payload diet bounds
- ID009  the finding-code inventory: every code registered by every
         pass (registry.all_codes) must appear in the README
         "## Static analysis" pass/code table, and every code-shaped
         token in that table must name a registered code — the table
         is where operators look up what a CI failure means, so a pass
         added without its row (or a row for a deleted code) rots the
         one documentation surface the lint itself points at. Range
         notation (`TS001`-`TS004`) covers the codes between its
         endpoints. Checked against the DEFAULT registry (out-of-tree
         registries document themselves); gated like HY003 — fixture
         trees without the section are only judged when they carry the
         real registry module
- ID010  the span-name inventory: every span name in
         core/spans.SPAN_NAMES (the pod-lifecycle tracing inventory)
         must appear in the metrics/metrics.py docstring entry for
         scheduler_trace_spans_total and in the README
         "## Distributed tracing" span table — the explain endpoint,
         the Perfetto export, and the runbook all key on these names,
         so a span added or renamed without its doc row leaves
         operators reading traces the docs cannot decode

The metric-registry half (ID001) imports the live package; pass
`{"metrics_runtime": False}` to skip it when linting fixture trees.
ID005 and ID010 are pure AST + file reads, so they run on fixture
trees too.
"""

from __future__ import annotations

import ast
import os
import re

from .core import Finding, LintContext
from .registry import PassBase

_NAME_RE = re.compile(r"\bscheduler_[a-z0-9_]+\b")

# Families that MUST exist: the durable-state (journal/snapshot) and
# leader-election surfaces are operational contracts — dashboards and
# the failover runbook depend on them, so their silent removal from the
# registry is a lint failure even though the two-way doc check would
# only notice if the docs were cleaned up in the same commit.
REQUIRED_FAMILIES = {
    "scheduler_journal_appends_total",
    "scheduler_journal_bytes_total",
    "scheduler_journal_fsync_seconds",
    "scheduler_journal_buffer_depth",
    "scheduler_journal_segments",
    "scheduler_snapshot_writes_total",
    "scheduler_snapshot_duration_seconds",
    "scheduler_snapshot_last_bytes",
    "scheduler_snapshot_last_restore_records",
    "scheduler_snapshot_last_restore_seconds",
    "scheduler_leader_state",
    "scheduler_leader_lease_age_seconds",
    # watchtower + build-identity floor: the alert counter is what the
    # rule engine fires into, build_info/uptime are what dashboards
    # correlate restarts against — all three are operational contracts
    "scheduler_build_info",
    "scheduler_uptime_seconds",
    "scheduler_alerts_total",
}

# dataclass fields that are structured sub-configs, not flat YAML keys
_STRUCTURED_FIELDS = {"profiles", "extenders"}
# top-level YAML keys that feed the structured fields above
_STRUCTURED_KEYS = {"profiles", "extenders"}


def camel(field: str) -> str:
    parts = field.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


def _key_matches(field: str, keys: set[str]) -> bool:
    if camel(field) in keys:
        return True
    if field.endswith("_seconds") and camel(field[: -len("_seconds")]) in keys:
        return True
    return False


def _field_matches(key: str, fields: set[str]) -> bool:
    snake = re.sub(r"([A-Z])", lambda m: "_" + m.group(1).lower(), key)
    return snake in fields or f"{snake}_seconds" in fields


class InventoryDriftPass(PassBase):
    name = "INVENTORY-DRIFT"
    codes = {
        "ID001": "metric registry drifted from docstring/README/"
                 "required-families inventory",
        "ID002": "SchedulerConfiguration fields drifted from "
                 "load_config YAML keys",
        "ID003": "cmd/main.py references an unknown config field or "
                 "CLI flag",
        "ID004": "config key / CLI flag undocumented in README",
        "ID005": "cycle-phase inventory drifted between observe.PHASES, "
                 "the trace lane mapping, the metrics docstring, and "
                 "the README",
        "ID006": "compile-cache key inventory drifted between "
                 "packing.SIGNATURE_DIMS, compile_cache.SIG_KEY_FIELDS, "
                 "and the README key table",
        "ID007": "degradation-rung inventory drifted between "
                 "degrade.RUNGS and the README rung table",
        "ID008": "sharded-collective budget inventory drifted between "
                 "audit.COLLECTIVE_BUDGETS, mesh.MESH_AXES, and the "
                 "README budget table",
        "ID009": "finding-code inventory drifted between the pass "
                 "registry and the README Static-analysis table",
        "ID010": "span-name inventory drifted between spans.SPAN_NAMES, "
                 "the metrics docstring, and the README tracing table",
        "ID011": "alert rule-pack inventory drifted between "
                 "rules.BUILTIN_RULES, the README alert table, and the "
                 "anomaly-class docs",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        findings: list[Finding] = []
        types_sf = self._find(ctx, "config/types.py")
        main_sf = self._find(ctx, "cmd/main.py")
        fields = self._config_fields(types_sf) if types_sf else {}
        keys = self._yaml_keys(types_sf) if types_sf else {}
        if types_sf:
            findings += self._check_config(types_sf, fields, keys)
        if main_sf:
            flags = self._cli_flags(main_sf)
            findings += self._check_main(main_sf, fields, flags)
            findings += self._check_readme(
                ctx, types_sf, main_sf, keys, flags
            )
        if self.args.get("metrics_runtime", True) and self._find(
            ctx, "metrics/metrics.py"
        ):
            findings += self._check_metrics(ctx)
        findings += self._check_phases(ctx)
        findings += self._check_spans(ctx)
        findings += self._check_compile_key(ctx)
        findings += self._check_rungs(ctx)
        findings += self._check_collective_budgets(ctx)
        findings += self._check_code_table(ctx)
        findings += self._check_alert_rules(ctx)
        return findings

    @staticmethod
    def _find(ctx: LintContext, suffix: str):
        for sf in ctx.files:
            if sf.rel.endswith(suffix):
                return sf
        return None

    # ---- ID002: config fields <-> YAML keys ------------------------------

    @staticmethod
    def _config_fields(sf) -> dict[str, int]:
        """SchedulerConfiguration field -> lineno."""
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef) and node.name == (
                "SchedulerConfiguration"
            ):
                return {
                    st.target.id: st.lineno
                    for st in node.body
                    if isinstance(st, ast.AnnAssign)
                    and isinstance(st.target, ast.Name)
                }
        return {}

    @staticmethod
    def _yaml_keys(sf) -> dict[str, int]:
        """Top-level `data.get("...")` keys in load_config -> lineno."""
        out: dict[str, int] = {}
        for node in sf.walk():
            if not (
                isinstance(node, ast.FunctionDef)
                and node.name == "load_config"
            ):
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                fn = call.func
                if (
                    isinstance(fn, ast.Attribute) and fn.attr == "get"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "data"
                    and call.args
                    and isinstance(call.args[0], ast.Constant)
                    and isinstance(call.args[0].value, str)
                ):
                    out.setdefault(call.args[0].value, call.lineno)
        return out

    def _check_config(self, sf, fields, keys) -> list[Finding]:
        findings = []
        for field, line in sorted(fields.items()):
            if field in _STRUCTURED_FIELDS:
                continue
            if not _key_matches(field, set(keys)):
                findings.append(Finding(
                    sf.rel, line, "ID002",
                    f"SchedulerConfiguration.{field} has no matching "
                    f"YAML key in load_config (expected "
                    f"{camel(field)!r}): the field is dead in every "
                    "config file",
                ))
        for key, line in sorted(keys.items()):
            if key in _STRUCTURED_KEYS:
                continue
            if not _field_matches(key, set(fields)):
                findings.append(Finding(
                    sf.rel, line, "ID002",
                    f"load_config reads YAML key {key!r} with no "
                    "matching SchedulerConfiguration field: the key "
                    "parses into nothing",
                ))
        return findings

    # ---- ID003: cmd/main.py coherence ------------------------------------

    @staticmethod
    def _cli_flags(sf) -> dict[str, int]:
        """'--flag-name' -> lineno for every add_argument call."""
        out: dict[str, int] = {}
        for node in sf.walk():
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("--")
            ):
                out[node.args[0].value] = node.lineno
        return out

    def _check_main(self, sf, fields, flags) -> list[Finding]:
        findings = []
        dests = {
            flag[2:].replace("-", "_") for flag in flags
        }
        for node in sf.walk():
            if not isinstance(node, ast.Attribute):
                continue
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "config"
                and fields and node.attr not in fields
            ):
                findings.append(Finding(
                    sf.rel, node.lineno, "ID003",
                    f"cmd/main.py references config.{node.attr}, which "
                    "is not a SchedulerConfiguration field: the "
                    "override writes into nothing",
                ))
            elif (
                isinstance(node.value, ast.Name)
                and node.value.id == "args"
                and dests and node.attr not in dests
            ):
                findings.append(Finding(
                    sf.rel, node.lineno, "ID003",
                    f"cmd/main.py reads args.{node.attr}, which no "
                    "add_argument flag defines",
                ))
        return findings

    # ---- ID004: README coverage ------------------------------------------

    def _check_readme(
        self, ctx, types_sf, main_sf, keys, flags
    ) -> list[Finding]:
        path = os.path.join(ctx.root, "README.md")
        if not os.path.exists(path):
            return []
        with open(path, encoding="utf-8") as f:
            text = f.read()
        findings = []
        for key, line in sorted(keys.items()):
            if key in _STRUCTURED_KEYS:
                continue
            if key not in text:
                findings.append(Finding(
                    types_sf.rel, line, "ID004",
                    f"YAML config key {key!r} is not documented "
                    "anywhere in README.md",
                ))
        for flag, line in sorted(flags.items()):
            if flag not in text:
                findings.append(Finding(
                    main_sf.rel, line, "ID004",
                    f"CLI flag {flag!r} is not documented anywhere in "
                    "README.md",
                ))
        return findings

    # ---- ID005: cycle-phase inventory ------------------------------------

    @staticmethod
    def _module_const(sf, name: str):
        """AST value of a module-level `NAME = <literal>` assignment:
        tuples of strings -> set of strings, dict literals -> set of
        string keys; None when absent or non-literal."""
        for node in sf.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
            ):
                continue
            v = node.value
            if isinstance(v, (ast.Tuple, ast.List)):
                return {
                    e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                }, node.lineno
            if isinstance(v, ast.Dict):
                return {
                    k.value for k in v.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                }, node.lineno
        return None, 0

    def _check_phases(self, ctx: LintContext) -> list[Finding]:
        obs_sf = self._find(ctx, "core/observe.py")
        if obs_sf is None:
            return []
        phases, obs_line = self._module_const(obs_sf, "PHASES")
        if not phases:
            return [Finding(
                obs_sf.rel, 1, "ID005",
                "core/observe.py defines no literal PHASES tuple — the "
                "phase inventory every surface is checked against",
            )]
        findings: list[Finding] = []

        fr_sf = self._find(ctx, "core/flight_recorder.py")
        if fr_sf is not None:
            lanes, fr_line = self._module_const(
                fr_sf, "TRACE_LANE_FOR_PHASE"
            )
            if lanes is None:
                findings.append(Finding(
                    fr_sf.rel, 1, "ID005",
                    "core/flight_recorder.py has no literal "
                    "TRACE_LANE_FOR_PHASE mapping: the trace export "
                    "cannot be checked against observe.PHASES",
                ))
            else:
                for p in sorted(phases - lanes):
                    findings.append(Finding(
                        fr_sf.rel, fr_line, "ID005",
                        f"phase {p!r} (observe.PHASES) is missing from "
                        "TRACE_LANE_FOR_PHASE: the trace export does "
                        "not know where to render it",
                    ))
                for p in sorted(lanes - phases):
                    findings.append(Finding(
                        fr_sf.rel, fr_line, "ID005",
                        f"TRACE_LANE_FOR_PHASE maps {p!r}, which is not "
                        "an observe.PHASES phase: stale lane mapping",
                    ))

        met_sf = self._find(ctx, "metrics/metrics.py")
        if met_sf is not None:
            doc = ast.get_docstring(met_sf.tree) or ""
            # scope to the scheduler_cycle_phase_seconds bullet so an
            # incidental word elsewhere cannot satisfy the check
            i = doc.find("scheduler_cycle_phase_seconds")
            region = doc[i:] if i >= 0 else ""
            j = region.find("\n- scheduler_")
            if j > 0:
                region = region[:j]
            for p in sorted(phases):
                if not re.search(rf"\b{re.escape(p)}\b", region):
                    findings.append(Finding(
                        met_sf.rel, 1, "ID005",
                        f"phase {p!r} (observe.PHASES) is not named in "
                        "the metrics docstring entry for "
                        "scheduler_cycle_phase_seconds",
                    ))

        path = os.path.join(ctx.root, "README.md")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                text = f.read()
            m = re.search(
                r"^## Observability\b(.*?)(?=^## |\Z)", text, re.M | re.S
            )
            section = m.group(1) if m else ""
            for p in sorted(phases):
                if not re.search(rf"\b{re.escape(p)}\b", section):
                    findings.append(Finding(
                        obs_sf.rel, obs_line, "ID005",
                        f"phase {p!r} (observe.PHASES) is not documented "
                        'in the README "## Observability" section',
                    ))
        return findings

    # ---- ID010: span-name inventory --------------------------------------

    def _check_spans(self, ctx: LintContext) -> list[Finding]:
        sp_sf = self._find(ctx, "core/spans.py")
        if sp_sf is None:
            return []
        names, sp_line = self._module_const(sp_sf, "SPAN_NAMES")
        if not names:
            return [Finding(
                sp_sf.rel, 1, "ID010",
                "core/spans.py defines no literal SPAN_NAMES tuple — "
                "the span inventory every surface is checked against",
            )]
        findings: list[Finding] = []

        met_sf = self._find(ctx, "metrics/metrics.py")
        if met_sf is not None:
            doc = ast.get_docstring(met_sf.tree) or ""
            # scope to the scheduler_trace_spans_total bullet so an
            # incidental word elsewhere cannot satisfy the check
            i = doc.find("scheduler_trace_spans")
            region = doc[i:] if i >= 0 else ""
            j = region.find("\n- scheduler_")
            if j > 0:
                region = region[:j]
            for n in sorted(names):
                if not re.search(rf"\b{re.escape(n)}\b", region):
                    findings.append(Finding(
                        met_sf.rel, 1, "ID010",
                        f"span {n!r} (spans.SPAN_NAMES) is not named in "
                        "the metrics docstring entry for "
                        "scheduler_trace_spans_total",
                    ))

        path = os.path.join(ctx.root, "README.md")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                text = f.read()
            m = re.search(
                r"^## Distributed tracing\b(.*?)(?=^## |\Z)",
                text, re.M | re.S,
            )
            section = m.group(1) if m else ""
            for n in sorted(names):
                if not re.search(rf"\b{re.escape(n)}\b", section):
                    findings.append(Finding(
                        sp_sf.rel, sp_line, "ID010",
                        f"span {n!r} (spans.SPAN_NAMES) is not documented "
                        'in the README "## Distributed tracing" section',
                    ))
        return findings

    # ---- ID006: compile-cache key inventory ------------------------------

    @staticmethod
    def _tuple_of_tuples_heads(sf, name: str):
        """First string element of each inner tuple of a module-level
        `NAME = ((..., ...), ...)` literal — the dimension names of
        packing.SIGNATURE_DIMS."""
        for node in sf.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
            ):
                continue
            if not isinstance(node.value, (ast.Tuple, ast.List)):
                return None, node.lineno
            out = set()
            for e in node.value.elts:
                if (
                    isinstance(e, (ast.Tuple, ast.List)) and e.elts
                    and isinstance(e.elts[0], ast.Constant)
                    and isinstance(e.elts[0].value, str)
                ):
                    out.add(e.elts[0].value)
            return out, node.lineno
        return None, 0

    def _check_compile_key(self, ctx: LintContext) -> list[Finding]:
        cc_sf = self._find(ctx, "core/compile_cache.py")
        pk_sf = self._find(ctx, "models/packing.py")
        if cc_sf is None or pk_sf is None:
            return []
        findings: list[Finding] = []
        dims, pk_line = self._tuple_of_tuples_heads(
            pk_sf, "SIGNATURE_DIMS"
        )
        sig_fields, cc_line = self._module_const(cc_sf, "SIG_KEY_FIELDS")
        extra_fields, _ = self._module_const(cc_sf, "EXTRA_KEY_FIELDS")
        if sig_fields is None:
            return [Finding(
                cc_sf.rel, 1, "ID006",
                "core/compile_cache.py defines no literal "
                "SIG_KEY_FIELDS tuple — the cache-key inventory the "
                "pad dimensions are checked against",
            )]
        if dims is None:
            return [Finding(
                pk_sf.rel, 1, "ID006",
                "models/packing.py defines no literal SIGNATURE_DIMS — "
                "the pad-dimension inventory the cache key must cover",
            )]
        for d in sorted(dims - sig_fields):
            findings.append(Finding(
                cc_sf.rel, cc_line, "ID006",
                f"pad dimension {d!r} (packing.SIGNATURE_DIMS) has no "
                "cache-key field in SIG_KEY_FIELDS: two regimes "
                f"differing only in {d} would alias one persistent "
                "executable entry",
            ))
        for d in sorted(sig_fields - dims):
            findings.append(Finding(
                pk_sf.rel, pk_line, "ID006",
                f"cache-key field {d!r} (SIG_KEY_FIELDS) names no "
                "SIGNATURE_DIMS dimension: stale key field",
            ))
        path = os.path.join(ctx.root, "README.md")
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                text = f.read()
            m = re.search(
                r"^## Compile-regime management\b(.*?)(?=^## |\Z)",
                text, re.M | re.S,
            )
            if m is None:
                findings.append(Finding(
                    cc_sf.rel, cc_line, "ID006",
                    'README.md has no "## Compile-regime management" '
                    "section documenting the cache-key table",
                ))
            else:
                section = m.group(1)
                for fld in sorted(sig_fields | (extra_fields or set())):
                    if not re.search(
                        rf"\b{re.escape(fld)}\b", section
                    ):
                        findings.append(Finding(
                            cc_sf.rel, cc_line, "ID006",
                            f"cache-key field {fld!r} is not documented "
                            'in the README "## Compile-regime '
                            'management" key table',
                        ))
        return findings

    # ---- ID007: degradation-rung inventory -------------------------------

    def _check_rungs(self, ctx: LintContext) -> list[Finding]:
        dg_sf = self._find(ctx, "core/degrade.py")
        if dg_sf is None:
            return []
        rungs, dg_line = self._module_const(dg_sf, "RUNGS")
        if not rungs:
            return [Finding(
                dg_sf.rel, 1, "ID007",
                "core/degrade.py defines no literal RUNGS tuple — the "
                "ladder inventory the README rung table is pinned to",
            )]
        path = os.path.join(ctx.root, "README.md")
        if not os.path.exists(path):
            return []
        with open(path, encoding="utf-8") as f:
            text = f.read()
        m = re.search(
            r"^## Failure model & degradation ladder\b(.*?)(?=^## |\Z)",
            text, re.M | re.S,
        )
        if m is None:
            return [Finding(
                dg_sf.rel, dg_line, "ID007",
                'README.md has no "## Failure model & degradation '
                'ladder" section documenting the rung table',
            )]
        section = m.group(1)
        findings: list[Finding] = []
        for rung in sorted(rungs):
            if not re.search(rf"\b{re.escape(rung)}\b", section):
                findings.append(Finding(
                    dg_sf.rel, dg_line, "ID007",
                    f"rung {rung!r} (degrade.RUNGS) is not documented "
                    'in the README "## Failure model & degradation '
                    'ladder" rung table',
                ))
        return findings

    # ---- ID008: sharded-collective budget inventory ----------------------

    def _check_collective_budgets(self, ctx: LintContext) -> list[Finding]:
        au_sf = self._find(ctx, "parallel/audit.py")
        if au_sf is None:
            return []
        budgets, au_line = self._module_const(
            au_sf, "COLLECTIVE_BUDGETS"
        )
        if not budgets:
            return [Finding(
                au_sf.rel, 1, "ID008",
                "parallel/audit.py defines no literal "
                "COLLECTIVE_BUDGETS dict — the committed allowlist "
                "scripts/audit_sharded.py gates the payload diet on",
            )]
        findings: list[Finding] = []
        mesh_sf = self._find(ctx, "parallel/mesh.py")
        axes: "set[str] | None" = None
        if mesh_sf is not None:
            axes, mesh_line = self._module_const(mesh_sf, "MESH_AXES")
            if axes is None:
                findings.append(Finding(
                    mesh_sf.rel, 1, "ID008",
                    "parallel/mesh.py defines no literal MESH_AXES "
                    "tuple — the axis-name inventory the budget table "
                    "and the sharding constraints are pinned to",
                ))
        path = os.path.join(ctx.root, "README.md")
        if not os.path.exists(path):
            return findings
        with open(path, encoding="utf-8") as f:
            text = f.read()
        m = re.search(
            r"^## Multi-chip and multi-host\b(.*?)(?=^## |\Z)",
            text, re.M | re.S,
        )
        if m is None:
            findings.append(Finding(
                au_sf.rel, au_line, "ID008",
                'README.md has no "## Multi-chip and multi-host" '
                "section documenting the collective budget table",
            ))
            return findings
        section = m.group(1)
        for cls in sorted(budgets):
            if not re.search(rf"\b{re.escape(cls)}\b", section):
                findings.append(Finding(
                    au_sf.rel, au_line, "ID008",
                    f"budget class {cls!r} (audit.COLLECTIVE_BUDGETS) "
                    'is not documented in the README "## Multi-chip '
                    'and multi-host" budget table',
                ))
        for axis in sorted(axes or ()):
            if not re.search(rf"\b{re.escape(axis)}\b", section):
                findings.append(Finding(
                    mesh_sf.rel, mesh_line, "ID008",
                    f"mesh axis {axis!r} (mesh.MESH_AXES) is not "
                    'documented in the README "## Multi-chip and '
                    'multi-host" section',
                ))
        return findings

    # ---- ID009: finding-code inventory -----------------------------------

    _REGISTRY_ANCHOR = "k8s_scheduler_tpu/analysis/registry.py"
    # the historical family prefixes: the phantom-row check only treats
    # tokens with one of these prefixes as finding codes, so prose like
    # "SHA256" in the section can never read as a stale row — while a
    # wholesale-deleted family's leftover rows are still caught
    _CODE_FAMILIES = ("TS", "LD", "JE", "ID", "HY", "RB", "TR", "SH")
    _CODE_RANGE_RE = re.compile(
        r"\b([A-Z]{2,3})(\d{3})`?\s*[-–]\s*`?\1(\d{3})\b"
    )

    def _check_code_table(self, ctx: LintContext) -> list[Finding]:
        from .registry import all_codes

        path = os.path.join(ctx.root, "README.md")
        if not os.path.exists(path):
            return []
        with open(path, encoding="utf-8") as f:
            text = f.read()
        m = re.search(
            r"^## Static analysis\b(.*?)(?=^## |\Z)", text, re.M | re.S
        )
        if m is None:
            # gated like HY003: only the real tree (which carries the
            # registry module) owes the README a Static-analysis table
            if ctx.file(self._REGISTRY_ANCHOR) is not None:
                return [Finding(
                    self._REGISTRY_ANCHOR, 1, "ID009",
                    'README.md has no "## Static analysis" section '
                    "documenting the pass/code table",
                )]
            return []
        section = m.group(1)
        registered = set(all_codes())
        prefixes = sorted(
            set(self._CODE_FAMILIES)
            | {re.match(r"[A-Z]+", c).group() for c in registered}
        )
        token_re = re.compile(
            rf"\b(?:{'|'.join(prefixes)})\d{{3}}\b"
        )
        documented = set(token_re.findall(section))
        # expand `TS001`-`TS004`-style ranges to the codes between
        for prefix, lo, hi in self._CODE_RANGE_RE.findall(section):
            for n in range(int(lo), int(hi) + 1):
                documented.add(f"{prefix}{n:03d}")
        findings: list[Finding] = []
        for code in sorted(registered - documented):
            findings.append(Finding(
                self._REGISTRY_ANCHOR, 1, "ID009",
                f"finding code {code!r} is registered but missing from "
                'the README "## Static analysis" pass/code table',
            ))
        for code in sorted(documented - registered):
            findings.append(Finding(
                self._REGISTRY_ANCHOR, 1, "ID009",
                f'the README "## Static analysis" table documents '
                f"{code!r}, which no registered pass defines: stale row",
            ))
        return findings

    # ---- ID011: alert rule-pack inventory --------------------------------

    @staticmethod
    def _rule_pack_names(sf):
        """Rule names out of the module-level `BUILTIN_RULES = (...)`
        literal: a tuple/list of dict literals whose "name" values are
        string constants. None when the literal is absent or not
        statically extractable — the rule pack MUST stay a pure
        literal, that is what makes it a machine-checked inventory."""
        for node in sf.tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "BUILTIN_RULES"
            ):
                continue
            v = node.value
            if not isinstance(v, (ast.Tuple, ast.List)):
                return None, node.lineno
            names: set[str] = set()
            for elt in v.elts:
                if not isinstance(elt, ast.Dict):
                    return None, node.lineno
                for k, val in zip(elt.keys, elt.values):
                    if (
                        isinstance(k, ast.Constant) and k.value == "name"
                        and isinstance(val, ast.Constant)
                        and isinstance(val.value, str)
                    ):
                        names.add(val.value)
            return names, node.lineno
        return None, 0

    # rule names the phantom-row scan recognizes: bare snake_case
    # tokens in the alert table's first column (family names carry the
    # scheduler_ prefix and belong to ID001's tables, not this one)
    _RULE_ROW_RE = re.compile(r"^\| *`([a-z][a-z0-9_]*)` *\|", re.M)

    def _check_alert_rules(self, ctx: LintContext) -> list[Finding]:
        rules_sf = self._find(ctx, "metrics/rules.py")
        if rules_sf is None:
            return []
        names, r_line = self._rule_pack_names(rules_sf)
        if not names:
            return [Finding(
                rules_sf.rel, max(r_line, 1), "ID011",
                "metrics/rules.py defines no statically-extractable "
                "BUILTIN_RULES literal (tuple of dict literals with "
                'string "name" values) — the committed rule pack the '
                "README alert table is pinned to",
            )]
        findings: list[Finding] = []
        # the anomaly-class leg: rule firings raise the `alert` class,
        # so its removal from observe.ANOMALY_CLASSES would make every
        # firing crash raise_anomaly's class validation
        obs_sf = self._find(ctx, "core/observe.py")
        if obs_sf is not None:
            classes, obs_line = self._module_const(
                obs_sf, "ANOMALY_CLASSES"
            )
            if classes is not None and "alert" not in classes:
                findings.append(Finding(
                    obs_sf.rel, max(obs_line, 1), "ID011",
                    'anomaly class "alert" is missing from '
                    "observe.ANOMALY_CLASSES — rule firings raise it, "
                    "so every alert would crash class validation",
                ))
        path = os.path.join(ctx.root, "README.md")
        if not os.path.exists(path):
            return findings
        with open(path, encoding="utf-8") as f:
            text = f.read()
        m = re.search(
            r"^### Metrics history, alert rules & the black box\b"
            r"(.*?)(?=^#{2,3} |\Z)",
            text, re.M | re.S,
        )
        if m is None:
            findings.append(Finding(
                rules_sf.rel, r_line, "ID011",
                'README.md has no "### Metrics history, alert rules & '
                'the black box" subsection documenting the built-in '
                "rule table",
            ))
            return findings
        section = m.group(1)
        for name in sorted(names):
            if not re.search(rf"\b{re.escape(name)}\b", section):
                findings.append(Finding(
                    rules_sf.rel, r_line, "ID011",
                    f"rule {name!r} (rules.BUILTIN_RULES) is not "
                    "documented in the README alert-rule table",
                ))
        for doc in sorted(set(self._RULE_ROW_RE.findall(section))):
            if doc.startswith("scheduler_"):
                continue  # family column rows belong to ID001
            if doc not in names:
                findings.append(Finding(
                    rules_sf.rel, r_line, "ID011",
                    f"the README alert-rule table documents {doc!r}, "
                    "which rules.BUILTIN_RULES does not define: "
                    "stale row",
                ))
        return findings

    # ---- ID001: metric inventory (runtime) -------------------------------

    def _check_metrics(self, ctx: LintContext) -> list[Finding]:
        problems = metric_inventory_problems(ctx.root)
        metrics_rel = self._find(ctx, "metrics/metrics.py").rel
        return [
            Finding(metrics_rel, 1, "ID001", p) for p in problems
        ]


# ---- the lint_metrics.py logic, kept importable for the shim -------------


def registered_names() -> set[str]:
    """Metric families registered on a fresh SchedulerMetrics, in
    Prometheus exposition naming (counters get their _total suffix)."""
    from k8s_scheduler_tpu.metrics import SchedulerMetrics

    names: set[str] = set()
    for fam in SchedulerMetrics().registry.collect():
        name = fam.name
        if fam.type == "counter":
            name += "_total"
        names.add(name)
    return names


def _strip_series_suffixes(names: set[str], families: set[str]) -> set[str]:
    """Collapse `foo_bucket`/`foo_count`/`foo_sum`/`foo_created` doc
    mentions onto their family name so prose quoting a specific series
    does not count as a phantom metric."""
    out = set()
    for n in names:
        base = re.sub(r"_(bucket|count|sum|created)$", "", n)
        out.add(base if base in families and n not in families else n)
    return out


def docstring_names() -> set[str]:
    import k8s_scheduler_tpu.metrics.metrics as mod

    return set(_NAME_RE.findall(mod.__doc__ or ""))


def readme_names(root: str | None = None) -> set[str]:
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    path = os.path.join(root, "README.md")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    m = re.search(r"^## Observability\b(.*?)(?=^## |\Z)", text,
                  re.M | re.S)
    if m is None:
        return set()
    return set(_NAME_RE.findall(m.group(1)))


def metric_inventory_problems(root: str | None = None) -> list[str]:
    """Human-readable metric-inventory drift complaints (empty = ok)."""
    reg = registered_names()
    problems: list[str] = []
    gone = sorted(REQUIRED_FAMILIES - reg)
    if gone:
        problems.append(
            "required durable-state/leader metric families no longer "
            f"registered: {gone}"
        )
    for surface, found in (
        ("metrics/metrics.py docstring", docstring_names()),
        ('README "## Observability" section', readme_names(root)),
    ):
        found = _strip_series_suffixes(found, reg)
        missing = sorted(reg - found)
        phantom = sorted(found - reg)
        if not found:
            problems.append(f"{surface}: no metric names found at all")
        if missing:
            problems.append(
                f"{surface}: registered but undocumented: {missing}"
            )
        if phantom:
            problems.append(
                f"{surface}: documented but not registered: {phantom}"
            )
    return problems
