"""ROBUSTNESS (RB0xx): failure handling must leave a trace.

RB001: in `core/`, `state/`, and `internal/`, a broad exception handler
(`except Exception` / bare `except:`) must LOG, COUNT A METRIC, or EMIT
AN EVENT somewhere in its body before swallowing or re-raising — the
silent-swallow pattern is how a consumed cycle, a dead writer thread,
or a dropped record disappears without an on-box trace (the exact gap
ISSUE 9's fetch-failure attribution closed). Handlers that transform
the error into an explicit `raise NewError(...)` pass too: the message
travels with the new exception.

Deliberately silent handlers are INVENTORIED, not outlawed: each needs
an inline `# schedlint: disable=RB001 -- why` on the `except` line, so
new silent swallows can't accumulate without a reviewed justification.

Detection is name-based and over-approximate, like the rest of the
framework: a call whose attribute/function name is in the known
logging / metric / event vocabularies counts as a trace. A helper with
an unknown name that "really does log" should either be named into the
vocabulary or carry a suppression — the cost of one pragma beats a
silent hole.
"""

from __future__ import annotations

import ast

from .core import Finding, LintContext
from .registry import PassBase

# package directories the rule applies to (matched as path segments, so
# fixture trees like pkg/core/x.py are covered the same way)
_TARGET_SEGMENTS = {"core", "state", "internal"}

# attribute names that count as leaving a trace
_LOG_ATTRS = {
    "exception", "warning", "error", "info", "debug", "critical", "log",
}
_METRIC_ATTRS = {"inc", "observe", "set", "labels", "observe_attempt"}
_EVENT_ATTRS = {
    "record", "system", "pod_event", "note", "failed_scheduling",
    "assume_expired", "scheduled", "preempted", "note_fetch_failure",
    "degrade", "raise_anomaly", "_cycle_failed", "note_unsupported",
}
_TRACE_ATTRS = _LOG_ATTRS | _METRIC_ATTRS | _EVENT_ATTRS
# bare function names that count (module-local helpers)
_TRACE_NAMES = {"_record_strike", "_pev", "print"}


def _is_broad_handler(h: ast.ExceptHandler) -> bool:
    """`except:` or `except Exception[ as e]:` (incl. dotted/builtin
    spellings and tuple members)."""
    t = h.type
    if t is None:
        return True

    def one(n) -> bool:
        if isinstance(n, ast.Name):
            return n.id in ("Exception", "BaseException")
        if isinstance(n, ast.Attribute):
            return n.attr in ("Exception", "BaseException")
        return False

    if isinstance(t, ast.Tuple):
        return any(one(e) for e in t.elts)
    return one(t)


def _leaves_trace(h: ast.ExceptHandler) -> bool:
    for node in ast.walk(h):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _TRACE_ATTRS:
                return True
            if isinstance(f, ast.Name) and f.id in _TRACE_NAMES:
                return True
        elif isinstance(node, ast.Raise) and node.exc is not None:
            # an explicit `raise NewError(...)` re-contextualizes the
            # failure loudly; a bare `raise` just forwards it silently
            return True
    return False


class RobustnessPass(PassBase):
    name = "ROBUSTNESS"
    codes = {
        "RB001": "bare `except Exception` in core//state//internal/ "
                 "swallows or re-raises without logging, counting a "
                 "metric, or emitting an event",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        findings: list[Finding] = []
        for sf in ctx.files:
            segments = sf.rel.split("/")[:-1]
            if not _TARGET_SEGMENTS & set(segments):
                continue
            for node in sf.walk():
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not _is_broad_handler(node):
                    continue
                if _leaves_trace(node):
                    continue
                findings.append(Finding(
                    sf.rel, node.lineno, "RB001",
                    "broad `except Exception` handler leaves no trace "
                    "(no log / metric / event) before swallowing or "
                    "re-raising — attribute the failure, or inventory "
                    "it with `# schedlint: disable=RB001 -- why`",
                ))
        return findings
