"""LOCK-DISCIPLINE (LD0xx): lock order + no blocking under a state lock.

PR 3 documented a strict acquisition order for the scheduler's state
locks — queue -> cache -> journal (state/manager.py docstring) — and a
bind-path rule that the journal append is a pure buffer push: fsync,
sleep, and file I/O belong to the writer thread, never to code holding
a state lock. Nothing enforced either; this pass does.

Per function (scoped to internal/, state/, core/flight_recorder by
default) it tracks the `with <lock>` nesting, resolves calls within the
scoped file set (name-based), and propagates each callee's transitive
acquisitions and blocking effects to its callers:

- LD001  acquiring a ranked lock while holding a higher-ranked one
         (an inversion of queue -> cache -> journal is an ABBA deadlock
         with the snapshot path, which holds queue+cache)
- LD002  a blocking call — os.fsync, time.sleep, open()/os file ops, a
         condition/event wait — made while any tracked lock is held

Lock identity is structural: an attribute chain ending in `_lock` /
`_cond` is a lock; a chain component naming queue/cache/journal (or
the defining module's basename) gives its rank. Unranked locks (e.g.
the flight recorder's timeline lock) still count as "held" for LD002.
Re-acquiring an already-held lock is allowed (queue/cache are RLocks).
"""

from __future__ import annotations

import ast
import os

from .callgraph import FuncInfo, attribute_chain
from .core import Finding, LintContext
from .registry import PassBase
from .effects import module_aliases as _module_aliases

_DEFAULT_SCOPE = ("internal/", "state/", "core/flight_recorder")
_RANK = {"queue": 0, "cache": 1, "journal": 2}
_BASENAME_OWNER = {
    "queue.py": "queue", "cache.py": "cache", "journal.py": "journal",
}
_LOCK_SUFFIXES = ("_lock", "_cond", "_condition")

# (dotted chain) -> human description of the blocking primitive
_BLOCKING_CHAINS = {
    ("os", "fsync"): "os.fsync",
    ("os", "replace"): "os.replace",
    ("os", "rename"): "os.rename",
    ("os", "unlink"): "os.unlink",
    ("os", "listdir"): "os.listdir",
    ("os", "makedirs"): "os.makedirs",
    ("os", "open"): "os.open",
    ("os", "fdopen"): "os.fdopen",
    ("socket", "create_connection"): "socket.create_connection",
    ("subprocess", "run"): "subprocess.run",
}


def blocking_effect(
    chain: tuple[str, ...], aliases: dict
) -> tuple[str, tuple[str, ...] | None] | None:
    """(description, waits-on lock CHAIN or None) when `chain` names a
    known blocking primitive, else None. The ONE classification ladder
    shared by LOCK-DISCIPLINE and RACES — a new blocking primitive (or
    an aliasing fix like the time.sleep handling) lands in both passes
    at once. Callers turn the waits-on chain into their own lock
    identity (ranked here, class-qualified in races.py)."""
    if chain == ("open",):
        return "open()", None
    if chain in _BLOCKING_CHAINS:
        return _BLOCKING_CHAINS[chain], None
    if (
        len(chain) == 2 and aliases.get(chain[0]) == "time"
        and chain[1] == "sleep"
    ) or (len(chain) == 1 and aliases.get(chain[0]) == "time.sleep"):
        return "time.sleep", None
    if len(chain) >= 2 and chain[-1] == "wait":
        return f"{'.'.join(chain)} wait", chain[:-1]
    return None


def lock_identity(
    chain: tuple[str, ...], rel: str
) -> str | None:
    """Lock name for an attribute chain, or None if it isn't one.
    Ranked locks return "queue"/"cache"/"journal"; everything else gets
    a stable unranked identity."""
    if not chain or not chain[-1].endswith(_LOCK_SUFFIXES):
        return None
    for part in chain[:-1]:
        low = part.lower()
        for owner in _RANK:
            if owner in low:
                return owner
    owner = _BASENAME_OWNER.get(os.path.basename(rel))
    if owner:
        return owner
    return f"{os.path.basename(rel)}:{'.'.join(chain)}"


class _Summary:
    __slots__ = ("acquires", "blocking")

    def __init__(self) -> None:
        # locks this function (transitively) acquires
        self.acquires: set[str] = set()
        # (description, waits_on_lock_or_None) blocking effects
        self.blocking: set[tuple[str, str | None]] = set()


class LockDisciplinePass(PassBase):
    name = "LOCK-DISCIPLINE"
    codes = {
        "LD001": "lock acquisition inverts the queue -> cache -> "
                 "journal order",
        "LD002": "blocking call (fsync/sleep/file I/O/wait) while "
                 "holding a state lock",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        scope = tuple(self.args.get("scope", _DEFAULT_SCOPE))
        index = ctx.index
        self._index = index
        self._scoped = {
            fid: f for fid, f in index.funcs.items()
            if any(s in f.file.rel for s in scope)
        }
        # name -> candidate funcs within scope (no generic blocklist:
        # the scoped set is small enough that name matches are signal)
        self._by_name: dict[str, list[FuncInfo]] = {}
        for f in self._scoped.values():
            if not f.name.startswith("<lambda"):
                self._by_name.setdefault(f.name, []).append(f)
        self._time_aliases = {}
        for sf in ctx.files:
            self._time_aliases[sf.rel] = _module_aliases(
                sf, {"time": "time"}
            )
        self._summaries: dict[str, _Summary] = {}
        self._in_progress: set[str] = set()
        findings: list[Finding] = []
        for fid in sorted(self._scoped):
            self._walk_function(self._scoped[fid], findings)
        return findings

    # ---- summaries (transitive effects) ----------------------------------

    def _summary(self, f: FuncInfo) -> _Summary:
        hit = self._summaries.get(f.id)
        if hit is not None:
            return hit
        if f.id in self._in_progress:  # recursion: break the cycle
            return _Summary()
        self._in_progress.add(f.id)
        s = _Summary()
        self._walk(f, list(f.node.body) if not isinstance(
            f.node, ast.Lambda) else [f.node.body], [], None, s)
        self._in_progress.discard(f.id)
        self._summaries[f.id] = s
        return s

    def _walk_function(
        self, f: FuncInfo, findings: list[Finding]
    ) -> None:
        s = _Summary()
        body = [f.node.body] if isinstance(f.node, ast.Lambda) \
            else list(f.node.body)
        self._walk(f, body, [], findings, s)
        self._summaries[f.id] = s

    # ---- the walk --------------------------------------------------------

    def _walk(
        self, f: FuncInfo, nodes: list[ast.AST], held: list[str],
        findings: list[Finding] | None, summary: _Summary,
    ) -> None:
        for node in nodes:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                       ast.ClassDef)
            ):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                # items acquire LEFT TO RIGHT: each item's check must see
                # the locks earlier items took (`with a, b:` is the same
                # ABBA surface as nested withs)
                cur_held = list(held)
                for item in node.items:
                    self._walk(
                        f, [item.context_expr], cur_held, findings,
                        summary,
                    )
                    chain = attribute_chain(item.context_expr)
                    lock = lock_identity(chain, f.file.rel) \
                        if chain else None
                    if lock is not None:
                        self._note_acquire(
                            f, lock, node.lineno, cur_held, findings,
                            summary,
                        )
                        cur_held = cur_held + [lock]
                self._walk(
                    f, list(node.body), cur_held, findings, summary
                )
                continue
            if isinstance(node, ast.Call):
                self._classify_call(f, node, held, findings, summary)
            self._walk(
                f, list(ast.iter_child_nodes(node)), held, findings,
                summary,
            )

    def _note_acquire(
        self, f: FuncInfo, lock: str, line: int, held: list[str],
        findings: list[Finding] | None, summary: _Summary,
        via: str | None = None,
    ) -> None:
        summary.acquires.add(lock)
        if lock in held:
            return  # re-entrant acquisition (RLocks)
        rank = _RANK.get(lock)
        if rank is None or findings is None:
            return
        above = [h for h in held if _RANK.get(h, -1) > rank]
        if above:
            tail = f" (via {via})" if via else ""
            findings.append(Finding(
                f.file.rel, line, "LD001",
                f"{f.qualname} acquires the {lock} lock while holding "
                f"{' + '.join(above)}{tail}: inverts the documented "
                "queue -> cache -> journal order (ABBA deadlock with "
                "the snapshot path)",
            ))

    def _note_blocking(
        self, f: FuncInfo, desc: str, waits_on: str | None, line: int,
        held: list[str], findings: list[Finding] | None,
        summary: _Summary, via: str | None = None,
    ) -> None:
        summary.blocking.add((desc, waits_on))
        if findings is None:
            return
        blockers = [h for h in held if h != waits_on]
        if blockers:
            tail = f" (via {via})" if via else ""
            findings.append(Finding(
                f.file.rel, line, "LD002",
                f"{f.qualname} makes a blocking call ({desc}){tail} "
                f"while holding the {' + '.join(blockers)} lock"
                f"{'s' if len(blockers) > 1 else ''}: blocking work "
                "belongs off the locked path (writer thread / after "
                "release)",
            ))

    def _classify_call(
        self, f: FuncInfo, node: ast.Call, held: list[str],
        findings: list[Finding] | None, summary: _Summary,
    ) -> None:
        chain = attribute_chain(node.func)
        if chain is None:
            return
        # direct blocking primitives (ladder shared with RACES)
        aliases = self._time_aliases.get(f.file.rel, {})
        eff = blocking_effect(chain, aliases)
        if eff is not None:
            desc, wchain = eff
            lock = lock_identity(wchain, f.file.rel) if wchain else None
            self._note_blocking(
                f, desc, lock, node.lineno, held, findings, summary
            )
            return
        # callee resolution within the scoped file set
        name = chain[-1]
        if name == "_journal":
            # the injected journal emitter: DurableState._emit at runtime
            name = "_emit"
        for target in self._by_name.get(name, ()):
            if target.id == f.id:
                continue
            ts = self._summary(target)
            for lock in sorted(ts.acquires):
                self._note_acquire(
                    f, lock, node.lineno, held, findings, summary,
                    via=target.qualname,
                )
            for desc, waits_on in sorted(
                ts.blocking, key=lambda x: (x[0], x[1] or "")
            ):
                self._note_blocking(
                    f, desc, waits_on, node.lineno, held, findings,
                    summary, via=target.qualname,
                )
