"""RACES (TR0xx, concurrency half): cross-role writes, lock-order
cycles, serve-loop blocking under contended locks.

The Python analogue of running the reference kube-scheduler's CI under
`go test -race`: the thread-role model from analysis/threads.py (which
thread executes which function, propagated over the shared call graph)
is intersected with lock_discipline.py's STRUCTURAL lock identities —
extended from the state//internal/ dirs to the whole tree (core/,
service/, cmd/, parallel/, scripts/) — to flag the three shapes every
PR since 3 has had to review by hand:

- TR001  a shared `self.<attr>` written under >= 2 roles with no lock
         identity common to every write site. Writes in `__init__` are
         construction (the threads do not exist yet) and exempt.
         Single-writer seqlock publications (FlightRecorder) and
         join-ordered handoffs (Journal.close after the writer join)
         are INVENTORIED with `# schedlint: disable=TR001 -- why`, the
         RB001 vocabulary — new unlocked cross-role writes cannot land
         without a reviewed justification.
- TR002  a lock-order inversion ANYWHERE in the tree: lock A taken
         while B is held somewhere and B taken while A is held
         somewhere else (the generalization of LD001 beyond the ranked
         queue -> cache -> journal order; ranked-pair inversions stay
         LD001's jurisdiction so one bug does not fire twice).
- TR004  a blocking call (fsync / sleep / file I/O / cond-wait /
         device fetch) on the SERVE-LOOP role while holding a lock a
         non-serve role also acquires — the shape that turns a slow
         disk or a wedged tunnel into a stalled serve loop AND a
         stalled background thread at once.

Lock identity is lock_discipline.lock_identity, qualified by the
enclosing class for unranked `self._lock`-style chains (two classes in
one file each with their own `_lock` are different locks; the ranked
queue/cache/journal identities still unify across spellings like
`self._lock` in queue.py vs `self._queue._lock` in manager.py).

Effects are propagated interprocedurally: a callee's transitive lock
acquisitions and blocking calls are charged to each call site with the
caller's held-lock set, exactly like lock_discipline — but resolved
through the precise call graph (lexical scope, import aliases,
self/cls methods) instead of the scoped by-name table.
"""

from __future__ import annotations

import ast
import dataclasses

from .callgraph import CodeIndex, FuncInfo, attribute_chain
from .core import Finding, LintContext
from .lock_discipline import _RANK, blocking_effect, lock_identity
from .registry import PassBase
from .threads import thread_roles
from .trace_safety import _module_aliases

# device->host fetches: the serve loop's one sanctioned blocking wait —
# blocking, but only TR004-relevant when a lock is held around them
_FETCH_CHAINS = {
    ("jax", "device_get"): "jax.device_get",
    ("jax", "block_until_ready"): "jax.block_until_ready",
}


def _qualified_lock(
    chain: tuple[str, ...], f: FuncInfo
) -> str | None:
    lock = lock_identity(chain, f.file.rel)
    if lock is None:
        return None
    if lock not in _RANK and chain and chain[0] in ("self", "cls") \
            and f.cls is not None:
        # class-qualify unranked instance locks so CompileWarmer._lock
        # and PodTimelines._lock (one file each) never alias
        return f"{lock}@{f.cls}"
    return lock


@dataclasses.dataclass
class _Effects:
    """One function's transitive lock/blocking/write effects."""

    acquires: set[str] = dataclasses.field(default_factory=set)
    # (description, waits_on_or_None)
    blocking: set[tuple[str, str | None]] = dataclasses.field(
        default_factory=set
    )


class _TreeWalker:
    """Whole-tree lock-aware walker: per function, records attribute
    writes, acquisition-order edges, and blocking sites, each with the
    held-lock set at that point."""

    def __init__(self, ctx: LintContext) -> None:
        self.ctx = ctx
        self.index: CodeIndex = ctx.index
        # (class_or_file, attr) -> [(fid, line, frozenset(held))]
        self.writes: dict[tuple[str, str], list] = {}
        # (outer_lock, inner_lock) -> first (file, line, qualname)
        self.order_edges: dict[tuple[str, str], tuple] = {}
        # fid -> [(desc, line, frozenset(held), waits_on)]
        self.blocking_sites: dict[str, list] = {}
        # lock -> set of fids that (transitively) acquire it
        self.acquired_by: dict[str, set[str]] = {}
        self._effects: dict[str, _Effects] = {}
        self._in_progress: set[str] = set()
        self._aliases = {
            sf.rel: _module_aliases(sf, {"time": "time"})
            for sf in ctx.files
        }

    def run(self) -> None:
        for fid in sorted(self.index.funcs):
            self._effects_of(self.index.funcs[fid])

    # ---- per-function ----------------------------------------------------

    def _effects_of(self, f: FuncInfo) -> _Effects:
        hit = self._effects.get(f.id)
        if hit is not None:
            return hit
        if f.id in self._in_progress:  # recursion: break the cycle
            return _Effects()
        self._in_progress.add(f.id)
        eff = _Effects()
        body = [f.node.body] if isinstance(f.node, ast.Lambda) \
            else list(f.node.body)
        self._walk(f, body, [], eff)
        self._in_progress.discard(f.id)
        self._effects[f.id] = eff
        for lock in eff.acquires:
            self.acquired_by.setdefault(lock, set()).add(f.id)
        return eff

    def _walk(
        self, f: FuncInfo, nodes: list, held: list[str], eff: _Effects
    ) -> None:
        for node in nodes:
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                       ast.ClassDef)
            ):
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                cur = list(held)
                for item in node.items:
                    self._walk(f, [item.context_expr], cur, eff)
                    chain = attribute_chain(item.context_expr)
                    lock = _qualified_lock(chain, f) if chain else None
                    if lock is not None:
                        self._note_acquire(f, lock, node.lineno, cur, eff)
                        cur = cur + [lock]
                self._walk(f, list(node.body), cur, eff)
                continue
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in ("self", "cls")
                    ):
                        owner = f.cls or f.file.rel
                        self.writes.setdefault(
                            (owner, t.attr), []
                        ).append((f.id, t.lineno, frozenset(held)))
            if isinstance(node, ast.Call):
                self._classify_call(f, node, held, eff)
            self._walk(f, list(ast.iter_child_nodes(node)), held, eff)

    def _note_acquire(
        self, f: FuncInfo, lock: str, line: int, held: list[str],
        eff: _Effects,
    ) -> None:
        eff.acquires.add(lock)
        if lock in held:
            return  # re-entrant (RLocks)
        for h in held:
            if h != lock:
                self.order_edges.setdefault(
                    (h, lock), (f.file.rel, line, f.qualname)
                )

    def _note_blocking(
        self, f: FuncInfo, desc: str, waits_on: str | None, line: int,
        held: list[str], eff: _Effects,
    ) -> None:
        eff.blocking.add((desc, waits_on))
        self.blocking_sites.setdefault(f.id, []).append(
            (desc, line, frozenset(held), waits_on)
        )

    def _classify_call(
        self, f: FuncInfo, node: ast.Call, held: list[str], eff: _Effects
    ) -> None:
        chain = attribute_chain(node.func)
        if chain is None:
            return
        aliases = self._aliases.get(f.file.rel, {})
        # the ladder shared with LOCK-DISCIPLINE, with the waits-on
        # chain qualified through THIS pass's class-aware identity
        shared = blocking_effect(chain, aliases)
        if shared is not None:
            desc, wchain = shared
            lock = _qualified_lock(wchain, f) if wchain else None
            self._note_blocking(f, desc, lock, node.lineno, held, eff)
            return
        if chain in _FETCH_CHAINS:
            self._note_blocking(
                f, _FETCH_CHAINS[chain], None, node.lineno, held, eff
            )
            return
        if (
            len(chain) >= 2 and chain[-1] == "join"
            and chain[-2] not in ("path", "sep", "linesep")
            and chain[:2] != ("os", "path")
        ):
            # thread-join blocking; the excluded bases are the string
            # joins (os.path.join and separator variables) that would
            # otherwise poison every path-building serve function
            self._note_blocking(
                f, f"{'.'.join(chain)} join", None, node.lineno, held, eff
            )
            return
        # interprocedural: charge callee effects to this call site
        targets = self.index.resolve_chain(f, chain)
        for tid in sorted(targets):
            target = self.index.funcs.get(tid)
            if target is None or target.id == f.id:
                continue
            teff = self._effects_of(target)
            for lock in sorted(teff.acquires):
                self._note_acquire(f, lock, node.lineno, held, eff)
            for desc, waits_on in sorted(
                teff.blocking, key=lambda x: (x[0], x[1] or "")
            ):
                # the callee's own sites already recorded it for the
                # callee; here it matters only if WE hold locks
                if held:
                    self._note_blocking(
                        f, f"{desc} (via {target.qualname})", waits_on,
                        node.lineno, held, eff,
                    )
                else:
                    eff.blocking.add((desc, waits_on))


class RacesPass(PassBase):
    name = "RACES"
    codes = {
        "TR001": "shared attribute written under >= 2 thread roles "
                 "with no common lock held",
        "TR002": "lock-order inversion (A->B and B->A observed, "
                 "beyond the LD001 ranked order)",
        "TR004": "serve-loop blocking call while holding a lock "
                 "another thread role contends",
    }

    def run(self, ctx: LintContext) -> list[Finding]:
        _sites, role_of = thread_roles(ctx)
        walker = _TreeWalker(ctx)
        walker.run()
        findings: list[Finding] = []
        findings += self._tr001(ctx, walker, role_of)
        findings += self._tr002(walker)
        findings += self._tr004(ctx, walker, role_of)
        return findings

    # ---- TR001 -----------------------------------------------------------

    def _tr001(self, ctx, walker, role_of) -> list[Finding]:
        index = ctx.index
        findings: list[Finding] = []
        for (owner, attr), sites in sorted(walker.writes.items()):
            by_fn: dict[str, list] = {}
            roles: set[str] = set()
            for fid, line, held in sites:
                f = index.funcs[fid]
                if f.name == "__init__":
                    continue  # construction precedes every spawn
                rs = role_of.get(fid)
                if not rs:
                    continue
                roles |= rs
                by_fn.setdefault(fid, []).append((line, held))
            if len(roles) < 2:
                continue
            common = None
            for fid, recs in by_fn.items():
                for _line, held in recs:
                    common = set(held) if common is None \
                        else common & held
            if common:
                continue  # every write site holds a shared lock
            for fid in sorted(by_fn):
                f = index.funcs[fid]
                line = min(l for l, _h in by_fn[fid])
                findings.append(Finding(
                    f.file.rel, line, "TR001",
                    f"{f.qualname} writes {owner}.{attr}, which is "
                    f"written under roles {{{', '.join(sorted(roles))}}} "
                    "with no lock identity common to every write site: "
                    "a cross-thread write-write race unless ordering is "
                    "guaranteed elsewhere (then inventory it: "
                    "# schedlint: disable=TR001 -- why)",
                ))
        return findings

    # ---- TR002 -----------------------------------------------------------

    def _tr002(self, walker) -> list[Finding]:
        findings: list[Finding] = []
        for (a, b), (file, line, qual) in sorted(
            walker.order_edges.items()
        ):
            if (b, a) not in walker.order_edges:
                continue
            if a in _RANK and b in _RANK:
                continue  # the ranked order is LD001's jurisdiction
            ofile, _oline, oqual = walker.order_edges[(b, a)]
            # the opposite site is named by file+qualname only: a line
            # number here would break the line-independent baseline/
            # fingerprint identity on every unrelated edit above it
            findings.append(Finding(
                file, line, "TR002",
                f"{qual} acquires {b} while holding {a}, but "
                f"{oqual} ({ofile}) acquires {a} while "
                f"holding {b}: an ABBA deadlock the moment the two "
                "paths run on different threads",
            ))
        return findings

    # ---- TR004 -----------------------------------------------------------

    def _tr004(self, ctx, walker, role_of) -> list[Finding]:
        index = ctx.index
        # lock -> roles that (transitively) acquire it
        lock_roles: dict[str, set[str]] = {}
        for lock, fids in walker.acquired_by.items():
            for fid in fids:
                lock_roles.setdefault(lock, set()).update(
                    role_of.get(fid, ())
                )
        findings: list[Finding] = []
        emitted: set[tuple] = set()
        for fid, sites in sorted(walker.blocking_sites.items()):
            if "serve" not in role_of.get(fid, ()):
                continue
            f = index.funcs[fid]
            for desc, line, held, waits_on in sites:
                contended = sorted(
                    h for h in held
                    if h != waits_on
                    and (lock_roles.get(h, set()) - {"serve"})
                )
                if not contended:
                    continue
                key = (f.file.rel, line, desc)
                if key in emitted:
                    continue
                emitted.add(key)
                others = sorted(set().union(*(
                    lock_roles.get(h, set()) for h in contended
                )) - {"serve"})
                findings.append(Finding(
                    f.file.rel, line, "TR004",
                    f"{f.qualname} (serve-loop role) makes a blocking "
                    f"call ({desc}) while holding "
                    f"{' + '.join(contended)}, which "
                    f"{{{', '.join(others)}}} also acquire"
                    f"{'s' if len(others) == 1 else ''}: a slow call "
                    "here stalls the serve loop AND every thread "
                    "waiting on that lock",
                ))
        return findings
