"""Device mesh + sharding layout for the scheduling program.

The reference's intra-process parallelism is a 16-goroutine `Parallelizer`
fanning Filter/Score over nodes (SURVEY.md §2 C6 — [UNVERIFIED], mount
empty); its distributed story is HTTPS to the API server. The TPU-native
equivalents (SURVEY.md §2 parallelism checklist, §5.8): the batched static
phase shards the **pods axis** across mesh devices (data-parallel masks and
scores; XLA inserts ICI collectives where the commit scan needs the full
row), and at 5k-node scale the **nodes axis** can shard on a second mesh
dimension. No NCCL/MPI — `jax.sharding` + XLA collectives only.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# module-level on purpose: mesh_pin runs INSIDE jitted programs, where
# a lazy first import is a trace-safety violation (schedlint TS001);
# this environment's sitecustomize imports jax at interpreter start
# anyway, so nothing is deferred in practice
import jax
from jax.sharding import NamedSharding, PartitionSpec

# The mesh-axis name inventory, pinned by schedlint ID008 against the
# collective budget allowlist (parallel/audit.COLLECTIVE_BUDGETS) and
# the README "## Multi-chip and multi-host" budget table: the pods axis
# is the data-parallel batch dimension every [P, ...] array shards on;
# the trailing nodes axis (2-D meshes) stays intra-host (JAX orders
# devices host-major) because the claim path's per-node collectives are
# the latency-critical ones. Renaming an axis without updating the
# budget allowlist would silently un-classify its collectives.
MESH_AXES = ("pods", "nodes")


def mesh_pin(arr, mesh, axes):
    """`with_sharding_constraint` an array onto named mesh axes, one
    per leading dim (None entries and dims beyond `axes` stay
    unconstrained). An axis applies only when the mesh carries it with
    size > 1 AND it divides that dim — otherwise the dim is pinned
    replicated, matching shard_snapshot's fallback. The ONE place the
    "which PartitionSpec does this array get" rule lives: the rounds
    engine's compacted views (ops/rounds.py shard_view) and the carry
    tables (core/cycle.py _constrain_carry) both delegate here, so the
    sharding rule cannot drift between the two layers."""
    spec = [None] * arr.ndim
    for d, axis in enumerate(axes[: arr.ndim]):
        if not axis:
            continue
        size = mesh.shape.get(axis, 1)
        if size > 1 and arr.shape[d] % size == 0:
            spec[d] = axis
    return jax.lax.with_sharding_constraint(
        arr, NamedSharding(mesh, PartitionSpec(*spec))
    )


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host (DCN) initialization (SURVEY.md §5.8).

    One scheduler process per TPU host; `jax.distributed.initialize` wires
    the hosts into one runtime so `jax.devices()` spans every chip and
    `make_mesh` lays axes over ICI within a host and DCN across hosts
    (JAX orders devices host-major, so the trailing mesh dimension stays
    intra-host — put the collective-heavy 'nodes' axis there). Arguments
    default to the standard JAX env vars (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID), so launchers that set those can
    call this with no arguments. A no-op on single-process deployments.

    Host-side state (queue/cache, the gRPC shim) stays on process 0 — the
    cluster-facing link is unchanged; only the device program spans hosts.
    """
    import os

    import jax

    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return  # single host
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(devices=None, nodes_axis: int = 1):
    """1-D ('pods',) mesh by default; pass nodes_axis>1 for a 2-D
    ('pods','nodes') mesh at large node counts."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if nodes_axis > 1:
        assert n % nodes_axis == 0
        arr = np.array(devices).reshape(n // nodes_axis, nodes_axis)
        return Mesh(arr, MESH_AXES)
    return Mesh(np.array(devices), MESH_AXES[:1])


def shard_snapshot(snap, mesh):
    """Lay out a ClusterSnapshot over the mesh: pod-axis arrays sharded on
    'pods' (and node-axis arrays on 'nodes' when the mesh has that axis);
    everything else replicated. Arrays whose leading dim doesn't divide the
    mesh axis stay replicated (tiny dedup tables are cheaper replicated
    than gathered)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    pods_size = mesh.shape["pods"]
    nodes_size = mesh.shape.get("nodes", 1)

    # multi-host meshes contain devices this process cannot address:
    # device_put of host data is single-process-only, so each process
    # contributes its local shards from the (replicated) host array —
    # the DCN path proven by tests/test_distributed.py
    me = jax.process_index()
    multiproc = any(
        d.process_index != me for d in np.asarray(mesh.devices).flat
    )

    def put(v, ns):
        if multiproc:
            return jax.make_array_from_callback(
                v.shape, ns, lambda idx: v[idx]
            )
        return jax.device_put(v, ns)

    out = {}
    for f in dataclasses.fields(snap):
        v = getattr(snap, f.name)
        if not isinstance(v, (np.ndarray, jax.Array)):
            out[f.name] = v
            continue
        spec = [None] * v.ndim
        if (
            f.name.startswith("pod_")
            and v.ndim >= 1
            and v.shape[0] % pods_size == 0
        ):
            spec[0] = "pods"
        elif (
            f.name.startswith("node_")
            and nodes_size > 1
            and v.ndim >= 1
            and v.shape[0] % nodes_size == 0
        ):
            spec[0] = "nodes"
        out[f.name] = put(v, NamedSharding(mesh, PartitionSpec(*spec)))
    return dataclasses.replace(snap, **{k: v for k, v in out.items()})
