"""Compiled-program collective audit: the sharded serving path's
payload accounting (ISSUE 10 / ROADMAP item 3).

At the "millions of users" cluster sizes the north star names, the
carry cycle's cross-device traffic — not FLOPs — is the cycle floor:
AUDIT_SHARDED_r05 measured ~43.2 MB of collectives per carry cycle at
the P=10112/N=5120 audit shape, 23.6 MB of it one all-reduce of the
replicated compacted [B, N] static base. This module turns that
accounting into a COMMITTED, compile-only gate:

- `parse_collectives` reads a compiled HLO module's text and returns
  one record per collective op (all-reduce / all-gather / reduce-
  scatter / all-to-all / collective-permute, sync and `-start` async
  forms, tuple-shaped results included) with element counts and bytes
  under two payload models: real dtype widths (`bytes`) and the r05
  artifact's flat 4-bytes-per-element model (`flat4` — kept so new
  audits stay comparable with the committed AUDIT_SHARDED_r05 total).
- `classify` buckets each record into the budget classes of
  `COLLECTIVE_BUDGETS` — the committed allowlist `scripts/
  audit_sharded.py` asserts against, pinned by schedlint ID008 to the
  README "## Multi-chip and multi-host" budget table and to the mesh
  axis names in `parallel/mesh.MESH_AXES` (renaming an axis or a class
  without its doc row fails the tree).
- `check_budgets` returns the violations (loud, named, per class).

The scheduler's per-regime program probe (`collective_payload_bytes`)
reuses the same parser to stamp flight records and the
`scheduler_collective_payload_bytes` gauge, so serving telemetry and
the CI gate can never disagree about what a byte of collective is.
"""

from __future__ import annotations

import dataclasses
import re

# Budget classes x per-cycle budgets (MB, REAL dtype widths) for the
# carry-cycle program at the audit shape (P=10112, N=5120, 8-device
# 1-D pods mesh — the AUDIT_SHARDED_r05 geometry). schedlint ID008
# pins every class name here to a row of the README "## Multi-chip and
# multi-host" budget table; scripts/audit_sharded.py asserts the
# measured per-class totals against these numbers and the grand total
# against TOTAL_BUDGET_MB. Calibration: measured post-diet values plus
# ~25% headroom, far below the 43.2 MB r05 baseline the acceptance
# criterion bounds (>= 30% reduction).
COLLECTIVE_BUDGETS = {
    # f32 planes of the [B, N]/[S, N] class: the compacted static-base
    # transport and the affinity-state count tables. Post-diet this is
    # ZERO — the compacted view stays sharded end-to-end (shard_view)
    # and the state update runs device-local (local_update_fn), where
    # r05 paid a 23.6 MB replicated-view all-reduce here. The budget is
    # small headroom, not an allowance: any [.,N]-wide f32 collective
    # reappearing is a diet regression and should trip this row.
    "static_base": 2.0,
    # claim/participant-table sort operands (packed u32 keys + index
    # permutations + per-claim key vectors) gathered across the pods
    # axis by the global sorts — measured 2.20 MB (index operands ride
    # at the minimal width the table extent allows: argsel.index_dtype)
    "claim_sort": 4.0,
    # capacity resolution: requested-vector [B, R] gathers and the
    # node_req [N, R] partial-sum reductions — measured 0.78 MB
    "capacity": 1.5,
    # boolean liveness/acceptance planes (pred all-reduces/gathers) —
    # measured 0.63 MB
    "predicates": 1.5,
    # sort-internal permute traffic (collective-permute lanes)
    "permute": 1.0,
    # anything unclassified — kept tight so a new heavy collective
    # cannot hide here
    "other": 1.0,
}
# grand total (real dtype widths). Measured 3.62 MB post-diet at the
# audit shape vs AUDIT_SHARDED_r05's 43.2 MB (-91%); the ISSUE 10
# acceptance bound is <= 30.2 MB (a 30% reduction) — this budget holds
# the diet at ~2x measured, an order of magnitude tighter.
TOTAL_BUDGET_MB = 8.0

_COLL_RE = re.compile(
    r"= (?P<type>.*?) (?P<op>(?:all-reduce|all-gather|reduce-scatter|"
    r"all-to-all|collective-permute)(?:-start)?)\("
)
_TENSOR_RE = re.compile(r"(pred|bf16|[fsu]\d+)\[([\d,]*)\]")

_WIDTH = {
    "pred": 1, "u8": 1, "s8": 1,
    "u16": 2, "s16": 2, "f16": 2, "bf16": 2,
    "u32": 4, "s32": 4, "f32": 4,
    "u64": 8, "s64": 8, "f64": 8,
}


@dataclasses.dataclass(frozen=True)
class Collective:
    op: str  # e.g. "all-reduce", "all-gather-start"
    type_str: str  # the HLO result type, tuple forms included
    elems: int  # total elements across the (possibly tuple) result
    bytes: int  # real dtype-width bytes
    flat4: int  # r05-comparable flat 4-bytes-per-element payload

    @property
    def base_op(self) -> str:
        return self.op[:-6] if self.op.endswith("-start") else self.op


def _tensors(type_str: str) -> list[tuple[str, list[int]]]:
    out = []
    for dt, dims in _TENSOR_RE.findall(type_str):
        shape = [int(d) for d in dims.split(",") if d] if dims else []
        out.append((dt, shape))
    return out


def parse_collectives(hlo_text: str) -> list[Collective]:
    """One record per collective op line of a compiled HLO module.
    Parsed per LINE so tuple-shaped (variadic/combined) collectives are
    covered; `-start` async halves are counted once (their `-done`
    partner carries no new payload and does not match the regex)."""
    out: list[Collective] = []
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if not m:
            continue
        elems = 0
        nbytes = 0
        for dt, shape in _tensors(m.group("type")):
            n = 1
            for d in shape:
                n *= d
            elems += n
            nbytes += n * _WIDTH.get(dt, 4)
        out.append(Collective(
            op=m.group("op"),
            type_str=m.group("type"),
            elems=elems,
            bytes=nbytes,
            flat4=elems * 4,
        ))
    return out


def collective_payload_bytes(hlo_text: str) -> int:
    """Total real-width collective payload of a compiled program — the
    per-regime cost probe the scheduler stamps on flight records and
    exports as `scheduler_collective_payload_bytes`."""
    return sum(c.bytes for c in parse_collectives(hlo_text))


def classify(coll: Collective, P: int, N: int) -> str:
    """Budget class of one collective at audit geometry (P, N).

    Heuristics keyed on what each class structurally looks like, not on
    exact shapes (pass counts and window sizes move between configs):
    2-D f32 planes whose column extent is N (or a shard of it) are the
    static-base transport; wide integer vectors/pairs scaling with P
    are sort-key/permutation gathers; narrow f32 [., R<=8] tables are
    capacity traffic; pred planes are liveness predicates; collective-
    permutes of u8/u16/u32 lanes are sort internals."""
    tensors = _tensors(coll.type_str)
    if coll.base_op == "collective-permute":
        return "permute"
    # [., R<=8] capacity tables fail the width guard on their own; any
    # f32 plane at node-scale width is static-base-class transport
    f32_2d_n = any(
        dt == "f32" and len(sh) == 2 and sh[1] >= max(N // 64, 64)
        for dt, sh in tensors
    )
    if f32_2d_n:
        return "static_base"
    if any(dt == "pred" for dt, _sh in tensors) and all(
        dt == "pred" for dt, _sh in tensors
    ):
        return "predicates"
    if any(
        dt == "f32" and len(sh) == 2 and sh[1] <= 8
        for dt, sh in tensors
    ):
        return "capacity"
    if all(dt in ("s32", "u32", "s16", "u16") for dt, _sh in tensors):
        return "claim_sort"
    return "other"


def classify_totals(
    colls: "list[Collective]", P: int, N: int
) -> dict[str, int]:
    """Per-class real-width byte totals (every COLLECTIVE_BUDGETS class
    present, zero-filled, so a budget row can never silently vanish
    from a report)."""
    out = {k: 0 for k in COLLECTIVE_BUDGETS}
    for c in colls:
        out[classify(c, P, N)] += c.bytes
    return out


def check_budgets(
    class_bytes: "dict[str, int]",
    total_budget_mb: float = TOTAL_BUDGET_MB,
) -> list[str]:
    """Violations of the committed allowlist (empty = within budget).
    An unknown class in `class_bytes` is itself a violation — the
    allowlist must grow deliberately, in the same commit."""
    problems: list[str] = []
    mb = 1024.0 * 1024.0
    for cls, nbytes in sorted(class_bytes.items()):
        budget = COLLECTIVE_BUDGETS.get(cls)
        if budget is None:
            problems.append(
                f"collective class {cls!r} is not in "
                f"COLLECTIVE_BUDGETS ({nbytes / mb:.2f} MB unbudgeted)"
            )
        elif nbytes / mb > budget:
            problems.append(
                f"collective class {cls!r} moves {nbytes / mb:.2f} MB "
                f"per cycle, over its {budget:.2f} MB budget"
            )
    total = sum(class_bytes.values()) / mb
    if total > total_budget_mb:
        problems.append(
            f"total collective payload {total:.2f} MB per cycle, over "
            f"the {total_budget_mb:.2f} MB budget"
        )
    return problems
