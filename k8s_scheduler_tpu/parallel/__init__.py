from .mesh import make_mesh, shard_snapshot  # noqa: F401
