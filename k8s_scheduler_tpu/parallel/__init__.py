from .audit import (  # noqa: F401
    COLLECTIVE_BUDGETS,
    collective_payload_bytes,
    parse_collectives,
)
from .mesh import MESH_AXES, make_mesh, shard_snapshot  # noqa: F401
