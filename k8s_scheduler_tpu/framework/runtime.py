"""Framework runtime: assembles enabled plugins into the fused cycle
program (the analogue of `framework/runtime/framework.go`'s
RunFilterPlugins/RunScorePlugins — [UNVERIFIED], mount empty; SURVEY.md §2
C6). Where the reference dispatches plugin callbacks per pod on 16
goroutines, this runtime asks each enabled plugin for its batched mask/
score fragments once per cycle and AND/weighted-sums them inside one jit —
plugin composition happens at trace time, parallelism comes from XLA."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from ..config import SchedulerConfiguration, default_plugins
from .interfaces import CycleContext, PluginBase
from .registry import Registry, default_registry

# Default-enabled plugins whose TPU kernels are scheduled but not landed:
# silently skipped when missing from the registry (unlike unknown names,
# which raise). Empty — every default plugin has a kernel.
PLANNED_PLUGINS: frozenset[str] = frozenset()


class Framework:
    def __init__(
        self,
        filters: list[PluginBase],
        scores: list[tuple[PluginBase, float]],
        post_filters: list[PluginBase] = (),
    ):
        self.filters = list(filters)
        self.scores = list(scores)
        self.post_filters = list(post_filters)

    @staticmethod
    def from_config(
        config: SchedulerConfiguration | None = None,
        scheduler_name: str = "default-scheduler",
        registry: Registry | None = None,
    ) -> "Framework":
        config = config or SchedulerConfiguration()
        registry = registry or default_registry()
        profile = config.profile(scheduler_name)
        defaults = default_plugins()
        args = profile.plugin_config

        def make(entries):
            out = []
            for e in entries:
                if e.name in registry.names():
                    out.append((registry.make(e.name, args.get(e.name)), e.weight))
                elif e.name in PLANNED_PLUGINS:
                    continue  # default-enabled, kernel not landed yet
                else:
                    # unknown names fail loudly (a typo must not silently
                    # change scheduling semantics) — same error Registry.make
                    # raises, reachable from the config path
                    registry.make(e.name)
            return out

        filters = [p for p, _ in make(profile.plugins.filter.resolve(defaults["filter"]))]
        scores = [
            (p, float(w)) for p, w in make(profile.plugins.score.resolve(defaults["score"]))
        ]
        post_filters = [
            p for p, _ in make(profile.plugins.post_filter.resolve(defaults["post_filter"]))
        ]
        return Framework(filters, scores, post_filters)

    # ---- trace-time assembly (called inside jit) ----

    @property
    def filter_names(self) -> list[str]:
        """Column names of the per-pod reject-count tables (filter order =
        upstream Filter execution order = first-rejector attribution)."""
        return [f.name for f in self.filters]

    def static(
        self, ctx: CycleContext
    ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Batched static masks/scores plus per-pod reject attribution.

        Returns (mask [P,N], score [P,N], rejects i32 [P,F]) where
        rejects[p,i] counts the nodes FIRST rejected for pod p by filter i —
        the batched analogue of upstream's per-node "first failing plugin"
        Status that feeds FailedScheduling events and queueing hints."""
        snap = ctx.snap
        base = jnp.broadcast_to(snap.node_valid[None, :], (snap.P, snap.N))
        per_filter = [f.static_mask(ctx) for f in self.filters]
        rejects = self.attribute_rejects(base, per_filter)
        mask = base
        for m in per_filter:
            if m is not None:
                mask = mask & m
        score = jnp.zeros((snap.P, snap.N), jnp.float32)
        for s, w in self.scores:
            v = s.static_score(ctx)
            if v is not None:
                score = score + w * v
        return mask, score, rejects

    def static_lean(
        self, ctx: CycleContext
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """static() without per-filter reject attribution: one fused AND
        chain (mask) + weighted sum (score). The latency-path cycle uses
        this (attribution lives in the separate diagnosis program), and
        the carry-update program runs it on dirty-row views."""
        snap = ctx.snap
        mask = jnp.broadcast_to(snap.node_valid[None, :], (snap.P, snap.N))
        for f in self.filters:
            m = f.static_mask(ctx)
            if m is not None:
                mask = mask & m
        score = jnp.zeros((snap.P, snap.N), jnp.float32)
        for s, w in self.scores:
            v = s.static_score(ctx)
            if v is not None:
                score = score + w * v
        return mask, score

    def _stateful_plugins(self) -> list[PluginBase]:
        # a plugin enabled at several points (e.g. InterPodAffinity filter +
        # score) owns ONE extra-state slot, keyed by name
        seen: dict[str, PluginBase] = {}
        for p in self.filters + [s for s, _ in self.scores]:
            seen.setdefault(p.name, p)
        return list(seen.values())

    def extra_init(self, ctx: CycleContext) -> dict[str, Any]:
        extra = {}
        for p in self._stateful_plugins():
            e = p.extra_init(ctx)
            if e is not None:
                extra[p.name] = e
        return extra

    def dyn(self, ctx: CycleContext, p, node_requested, extra, static_row):
        """Returns (mask [N], score [N], rejects i32 [F]) — `rejects[i]`
        counts nodes first rejected by filter i's DYNAMIC mask at this scan
        step (nodes already statically rejected are attributed by
        `static`; the two tables add up per filter name)."""
        snap = ctx.snap
        mask = static_row
        rejects = []
        for f in self.filters:
            m = f.dyn_mask(ctx, p, node_requested, extra)
            if m is None:
                rejects.append(jnp.int32(0))
            else:
                newly = mask & ~m
                rejects.append(jnp.sum(newly, dtype=jnp.int32))
                mask = mask & m
        score = jnp.zeros((snap.N,), jnp.float32)
        for s, w in self.scores:
            # dyn_score sees the FULL feasibility row (static & dynamic) so
            # cross-node normalization covers feasible nodes only, like
            # upstream NormalizeScore running after Filter
            v = s.dyn_score(ctx, p, node_requested, extra, mask)
            if v is not None:
                score = score + w * v
        return mask, score, jnp.stack(rejects)

    def extra_update(self, ctx: CycleContext, extra, p, node, committed):
        out = dict(extra)
        for pl in self._stateful_plugins():
            if pl.name in out:
                out[pl.name] = pl.extra_update(ctx, out[pl.name], p, node, committed)
        return out

    # ---- batched dynamic path (round-based commit) ----

    def check_batched_parity(self) -> None:
        """Fail fast when a plugin implements a per-pod dynamic hook but
        not its batched counterpart: in rounds mode the batched path is
        the only one that runs, and a silently-skipped constraint would
        produce invalid placements with no error."""
        from .interfaces import PluginBase

        pairs = [
            ("dyn_mask", "dyn_mask_batched"),
            ("dyn_score", "dyn_score_batched"),
            ("extra_update", "extra_update_batched"),
        ]
        for p in self.filters + [s for s, _ in self.scores]:
            for single, batched in pairs:
                overrides_single = getattr(type(p), single) is not getattr(
                    PluginBase, single
                )
                overrides_batched = getattr(type(p), batched) is not getattr(
                    PluginBase, batched
                )
                if overrides_single and not overrides_batched:
                    raise TypeError(
                        f"plugin {p.name!r} implements {single} but not "
                        f"{batched}: its constraint would be silently "
                        f"dropped by the rounds commit engine. Implement "
                        f"{batched} or run with commit_mode='scan'."
                    )

    def dyn_batched(self, ctx: CycleContext, node_requested, extra,
                    static_mask):
        """Whole-pending-set analogue of `dyn`: returns (mask [P,N],
        score [P,N], per_filter list of [P,N] masks or None in filter
        order — the latter feeds reject attribution)."""
        snap = ctx.snap
        shared: dict = {}
        mask = static_mask
        per_filter = []
        for f in self.filters:
            m = f.dyn_mask_batched(ctx, node_requested, extra, shared)
            per_filter.append(m)
            if m is not None:
                mask = mask & m
        score = jnp.zeros((snap.P, snap.N), jnp.float32)
        for s, w in self.scores:
            v = s.dyn_score_batched(ctx, node_requested, extra, mask, shared)
            if v is not None:
                score = score + w * v
        return mask, score, per_filter

    def attribute_rejects(self, base_mask, per_filter, rows=None):
        """First-rejector attribution over a filter-mask chain: returns
        i32 [P, F] where column i counts the nodes newly rejected by
        filter i (None entries contribute zeros). `rows` (bool [P])
        restricts attribution to those pods. The single owner of the
        chain/column convention used by static(), dyn() and the rounds
        engine's final pass."""
        mask = base_mask
        cols = []
        for m in per_filter:
            if m is None:
                cols.append(jnp.zeros((base_mask.shape[0],), jnp.int32))
            else:
                newly = mask & ~m
                c = jnp.sum(newly, axis=1, dtype=jnp.int32)
                cols.append(c if rows is None else jnp.where(rows, c, 0))
                mask = mask & m
        return jnp.stack(cols, axis=1)

    def score_anchor(self, ctx: CycleContext, node_requested):
        """Weighted sum of the enabled score plugins' node-local capacity
        components (f32 [N]), or None when no plugin has one. See
        PluginBase.score_node_anchor."""
        total = None
        for s, w in self.scores:
            a = s.score_node_anchor(ctx, node_requested)
            if a is not None:
                total = w * a if total is None else total + w * a
        return total

    def extra_update_batched(self, ctx: CycleContext, extra, accepted,
                             node_of):
        out = dict(extra)
        for pl in self._stateful_plugins():
            if pl.name in out:
                out[pl.name] = pl.extra_update_batched(
                    ctx, out[pl.name], accepted, node_of
                )
        return out

    def post_filter(self, ctx: CycleContext, assignment, node_requested,
                    gate_rows, excluded=None):
        """Run PostFilter plugins in order; first non-None result wins
        (upstream RunPostFilterPlugins stops at the first nomination)."""
        for p in self.post_filters:
            r = p.post_filter(ctx, assignment, node_requested, gate_rows,
                              excluded)
            if r is not None:
                return r
        return None
