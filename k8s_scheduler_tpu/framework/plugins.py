"""The default plugin set, mirroring the reference's plugin names
(SURVEY.md §2 C7/C8: NodeUnschedulable, NodeName, NodePorts,
NodeResourcesFit, NodeAffinity, TaintToleration, ImageLocality,
NodeResourcesBalancedAllocation, InterPodAffinity, PodTopologySpread,
DefaultPreemption; expected upstream `framework/plugins/<name>/` —
[UNVERIFIED], mount empty).

Each plugin contributes mask/score fragments to the single fused cycle
program (see interfaces.py for the extension-point mapping)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops import images as images_ops
from ..ops import interpod as interpod_ops
from ..ops import labels as labels_ops
from ..ops import ports as ports_ops
from ..ops import preemption as preemption_ops
from ..ops import resources as res_ops
from ..ops import taints as taints_ops
from ..ops import volumes as volumes_ops
from .interfaces import CycleContext, PluginBase


def _score_resource_weights(snap, args: dict) -> jnp.ndarray:
    """score_resources arg -> one-hot f32 [R] weight vector (cpu+memory by
    default, matching upstream defaultRequestedRatioResources). Shared by
    every resource-scoring plugin so the semantics can't drift."""
    score_resources = args.get("score_resources", ("cpu", "memory"))
    w = np.zeros(len(snap.resource_names), np.float32)
    for r in score_resources:
        if r in snap.resource_names:
            w[snap.resource_names.index(r)] = 1.0
    return jnp.asarray(w)


class NodeUnschedulable(PluginBase):
    """Excludes cordoned nodes (`spec.unschedulable`). Upstream admits pods
    tolerating the node.kubernetes.io/unschedulable taint; that refinement
    rides on the toleration tables once the taint is synthesized — for now
    cordoned nodes are excluded unconditionally (oracle matches)."""

    name = "NodeUnschedulable"

    def static_mask(self, ctx: CycleContext):
        snap = ctx.snap
        P = snap.P
        return jnp.broadcast_to(~snap.node_unschedulable[None, :], (P, snap.N))


class NodeName(PluginBase):
    name = "NodeName"

    def static_mask(self, ctx: CycleContext):
        snap = ctx.snap
        pinned = snap.pod_node_name[:, None]  # [P, 1]
        node_ids = jnp.arange(snap.N, dtype=jnp.int32)[None, :]
        mask = jnp.ones((snap.P, snap.N), bool)
        mask = jnp.where(pinned >= 0, node_ids == pinned, mask)
        return jnp.where(pinned == -2, False, mask)  # named node unknown


class NodePorts(PluginBase):
    """hostPort conflicts: against EXISTING pods via the static mask,
    against pods committed earlier in this cycle via a [N, Q] port-claim
    bitmap carried through the commit scan (Q = distinct pending ports) —
    so intra-batch conflicts resolve exactly like the reference's
    sequential NodeInfo updates."""

    name = "NodePorts"

    def static_mask(self, ctx: CycleContext):
        snap = ctx.snap
        return ~ports_ops.ports_conflict_mask(snap.pod_ports, snap.node_used_ports)

    def extra_init(self, ctx: CycleContext):
        snap = ctx.snap
        return jnp.zeros((snap.N, snap.num_distinct_ports), bool)

    def dyn_mask(self, ctx: CycleContext, p, node_requested, extra):
        snap = ctx.snap
        claimed = extra[self.name]  # [N, Q]
        ids = snap.pod_port_ids[p]  # [MPorts]
        want = claimed[:, jnp.clip(ids, 0, claimed.shape[1] - 1)]  # [N, MPorts]
        return ~jnp.any(want & (ids >= 0)[None, :], axis=1)

    def extra_update(self, ctx: CycleContext, extra, p, node, committed):
        snap = ctx.snap
        ids = snap.pod_port_ids[p]
        safe = jnp.clip(ids, 0, extra.shape[1] - 1)
        add = committed & (ids >= 0)
        return extra.at[node, safe].max(add)

    # --- batched (rounds) path ---

    @staticmethod
    def _port_onehot(snap):  # bool [P, Q]
        Q = snap.num_distinct_ports
        P = snap.P
        ids = snap.pod_port_ids  # [P, MPorts]
        oh = jnp.zeros((P, Q), bool)
        pid = jnp.broadcast_to(
            jnp.arange(P, dtype=jnp.int32)[:, None], ids.shape
        )
        return oh.at[pid, jnp.clip(ids, 0, Q - 1)].max(ids >= 0)

    def dyn_mask_batched(self, ctx: CycleContext, node_requested, extra,
                         shared):
        snap = ctx.snap
        claimed = extra[self.name]  # [N, Q]
        oh = shared.setdefault("port_onehot", self._port_onehot(snap))
        conflict = (
            oh.astype(jnp.float32) @ claimed.T.astype(jnp.float32)
        ) > 0.0  # [P, N]
        return ~conflict

    def extra_update_batched(self, ctx: CycleContext, extra, accepted,
                             node_of):
        snap = ctx.snap
        ids = snap.pod_port_ids  # [P, MPorts]
        Q = extra.shape[1]
        nsafe = jnp.clip(node_of, 0, extra.shape[0] - 1)
        nidx = jnp.broadcast_to(nsafe[:, None], ids.shape)
        add = accepted[:, None] & (ids >= 0)
        return extra.at[nidx, jnp.clip(ids, 0, Q - 1)].max(add)


class NodeResourcesFit(PluginBase):
    """Filter: resource fit against the RUNNING allocatable (in-scan).
    Score: the configured scoring strategy (LeastAllocated default,
    MostAllocated for bin-packing), also in-scan."""

    name = "NodeResourcesFit"

    def dyn_mask(self, ctx: CycleContext, p, node_requested, extra):
        snap = ctx.snap
        return res_ops.fit_mask_single(
            snap.pod_requested[p], snap.node_allocatable, node_requested
        )

    def _strategy_fn(self):
        strategy = self.args.get("scoring_strategy", "LeastAllocated")
        return (
            res_ops.most_requested_score
            if strategy == "MostAllocated"
            else res_ops.least_requested_score
        )

    def dyn_score(self, ctx: CycleContext, p, node_requested, extra, feasible):
        snap = ctx.snap
        return self._strategy_fn()(
            snap.pod_requested[p],
            snap.node_allocatable,
            node_requested,
            _score_resource_weights(snap, self.args),
        )

    def dyn_mask_batched(self, ctx: CycleContext, node_requested, extra,
                         shared):
        snap = ctx.snap
        return res_ops.fit_mask(
            snap.pod_requested, snap.node_allocatable, node_requested
        )

    def dyn_score_batched(self, ctx: CycleContext, node_requested, extra,
                          feasible, shared):
        snap = ctx.snap
        return self._strategy_fn()(
            snap.pod_requested[:, None, :],
            snap.node_allocatable,
            node_requested,
            _score_resource_weights(snap, self.args),
        )

    def score_node_anchor(self, ctx: CycleContext, node_requested):
        snap = ctx.snap
        return self._strategy_fn()(
            jnp.zeros_like(snap.node_allocatable[:1, :1]),  # zero pod
            snap.node_allocatable,
            node_requested,
            _score_resource_weights(snap, self.args),
        )


class NodeResourcesBalancedAllocation(PluginBase):
    name = "NodeResourcesBalancedAllocation"

    def dyn_score(self, ctx: CycleContext, p, node_requested, extra, feasible):
        snap = ctx.snap
        return res_ops.balanced_allocation_score(
            snap.pod_requested[p], snap.node_allocatable, node_requested,
            _score_resource_weights(snap, self.args),
        )

    def dyn_score_batched(self, ctx: CycleContext, node_requested, extra,
                          feasible, shared):
        snap = ctx.snap
        return res_ops.balanced_allocation_score(
            snap.pod_requested[:, None, :], snap.node_allocatable,
            node_requested, _score_resource_weights(snap, self.args),
        )

    def score_node_anchor(self, ctx: CycleContext, node_requested):
        snap = ctx.snap
        return res_ops.balanced_allocation_score(
            jnp.zeros_like(snap.node_allocatable[:1, :1]),
            snap.node_allocatable, node_requested,
            _score_resource_weights(snap, self.args),
        )


class NodeAffinity(PluginBase):
    name = "NodeAffinity"

    def static_mask(self, ctx: CycleContext):
        return labels_ops.pod_requirement_mask(ctx.snap, ctx.expr_node_mask)

    def static_score(self, ctx: CycleContext):
        return labels_ops.preferred_score(ctx.snap, ctx.expr_node_mask)


class VolumeBinding(PluginBase):
    """PVC/PV feasibility (ops/volumes.py): bound-PV node affinity,
    static-PV candidacy, and dynamic-provisioning topology for
    WaitForFirstConsumer claims. The static mask covers pre-cycle
    availability; a `pv_claimed` bitmap in the commit engines' extra
    state arbitrates SAME-CYCLE claimants of one static PV (a placed pod
    claims its lowest-index compatible PV; later pods see it taken —
    upstream resolves this one pod later at PreBind via bind failure)."""

    name = "VolumeBinding"

    def static_mask(self, ctx: CycleContext):
        if not ctx.snap.has_volumes:
            return None
        return volumes_ops.volume_mask(ctx.snap, ctx.expr_node_mask)

    def _has_static_claims(self, snap) -> bool:
        # claim tracking only matters when unbound WFC slots AND static
        # PVs exist at all; otherwise the state is dead weight
        return bool(snap.has_volumes and snap.pv_avail.shape[0] > 0)

    def extra_init(self, ctx: CycleContext):
        if not self._has_static_claims(ctx.snap):
            return None
        return jnp.zeros((ctx.snap.pv_avail.shape[0],), bool)

    def dyn_mask(self, ctx: CycleContext, p, node_requested, extra):
        if not self._has_static_claims(ctx.snap):
            return None
        # per-pod ROW form: the scan calls this once per step, and the
        # batched [P, N] form would redo full-set work P times
        return volumes_ops.volume_mask_unbound_row(
            ctx.snap, ctx.expr_node_mask, extra[self.name], p
        )

    def dyn_mask_batched(self, ctx: CycleContext, node_requested, extra,
                         shared):
        if not self._has_static_claims(ctx.snap):
            return None
        return volumes_ops.volume_mask_unbound(
            ctx.snap, ctx.expr_node_mask, extra[self.name]
        )

    def extra_update(self, ctx: CycleContext, extra, p, node, committed):
        if extra is None:
            return extra
        snap = ctx.snap
        claimed = extra
        MVol = snap.pod_vol_mode.shape[1]
        multi = MVol >= 2 and snap.has_multi_volume
        # slots claim in index order; multi-volume pods use the SDR-safe
        # choice (greedy lowest-index claiming can dead-end even when the
        # Hall mask admitted the pod — see ops/volumes.chosen_pv_sdr)
        pending = snap.pod_vol_mode[p] == 1  # [MVol]
        for t in range(MVol):
            if multi:
                ch = volumes_ops.chosen_pv_sdr_row(
                    snap, ctx.expr_node_mask, claimed, node, p, pending, t
                )
            else:
                ch = volumes_ops.chosen_pv_row(
                    snap, ctx.expr_node_mask, claimed, node, p, t
                )
            ch = jnp.where(committed, ch, -1)
            claimed = claimed.at[jnp.clip(ch, 0, claimed.shape[0] - 1)].max(
                ch >= 0
            )
            pending = pending.at[t].set(False)
        return claimed

    def extra_update_batched(self, ctx: CycleContext, extra, accepted,
                             node_of):
        if extra is None:
            return extra
        snap = ctx.snap
        # fixed-point fold: exact for ANY batch (diagnosis replays a
        # whole cycle's placements at once, where same-class claimants
        # contend); under the rounds engine's _RB_PV guard the batch is
        # claim-disjoint and the loop exits after one pass
        return volumes_ops.fold_pv_claims(
            snap, ctx.expr_node_mask, extra, accepted, node_of,
            snap.pod_order.astype("int32"),
        )


class TaintToleration(PluginBase):
    name = "TaintToleration"

    def static_mask(self, ctx: CycleContext):
        return taints_ops.taint_filter_mask(ctx.snap)

    def static_score(self, ctx: CycleContext):
        return taints_ops.taint_score(ctx.snap)


class ImageLocality(PluginBase):
    name = "ImageLocality"

    def static_score(self, ctx: CycleContext):
        return images_ops.image_locality_score(ctx.snap)


# --- shared affinity-state plumbing -----------------------------------------
# InterPodAffinity and PodTopologySpread both consume the per-(selector,
# domain) count state; whichever is initialized FIRST (filter order) owns
# the scan-carried slot and maintains it, the other reads it.

_AFFINITY_OWNER_KEY = "__affinity_state_owner__"


def _claim_affinity_state(ctx: CycleContext, name: str):
    snap = ctx.snap
    if not (snap.has_inter_pod_affinity or snap.has_topology_spread):
        return None
    owner = ctx._cache.get(_AFFINITY_OWNER_KEY)
    if owner is not None and owner != name:
        return None  # someone else owns the slot
    ctx._cache[_AFFINITY_OWNER_KEY] = name
    return ctx.initial_affinity_state()


def _affinity_state(ctx: CycleContext, extra):
    return extra[ctx._cache[_AFFINITY_OWNER_KEY]]


def _update_affinity_state(ctx: CycleContext, name, state, p, node, committed):
    if ctx._cache.get(_AFFINITY_OWNER_KEY) != name:
        return state
    return interpod_ops.affinity_update(
        ctx.snap, state, ctx.matched_pending, p, node, committed
    )


def _update_affinity_state_batched(ctx: CycleContext, name, state, accepted,
                                   node_of):
    if ctx._cache.get(_AFFINITY_OWNER_KEY) != name:
        return state
    return interpod_ops.affinity_update_batched(
        ctx.snap, state, ctx.matched_pending, accepted, node_of
    )


def _shared_cbn(ctx: CycleContext, state, shared):
    """counts-by-node [K*S, N] for the current round, computed once and
    shared between InterPodAffinity and PodTopologySpread."""
    if "cbn" not in shared:
        shared["cbn"] = interpod_ops.counts_by_node(ctx.snap, state)
    return shared["cbn"]


class InterPodAffinity(PluginBase):
    """The quadratic hot path, as counts over (selector, topology-domain)
    instead of pairwise pod comparisons — see ops/interpod.py."""

    name = "InterPodAffinity"

    def extra_init(self, ctx: CycleContext):
        return _claim_affinity_state(ctx, self.name)

    def dyn_mask(self, ctx: CycleContext, p, node_requested, extra):
        if not ctx.snap.has_inter_pod_affinity:
            return None
        return interpod_ops.affinity_dyn_mask(
            ctx.snap, _affinity_state(ctx, extra), ctx.matched_pending, p
        )

    def dyn_score(self, ctx: CycleContext, p, node_requested, extra, feasible):
        if not ctx.snap.has_inter_pod_affinity:
            return None
        return interpod_ops.affinity_dyn_score(
            ctx.snap, _affinity_state(ctx, extra), ctx.matched_pending, p, feasible
        )

    def extra_update(self, ctx: CycleContext, extra, p, node, committed):
        return _update_affinity_state(ctx, self.name, extra, p, node, committed)

    def dyn_mask_batched(self, ctx: CycleContext, node_requested, extra,
                         shared):
        if not ctx.snap.has_inter_pod_affinity:
            return None
        state = _affinity_state(ctx, extra)
        cbn = _shared_cbn(ctx, state, shared)
        return interpod_ops.affinity_mask_batched(
            ctx.snap, state, ctx.matched_pending, cbn
        )

    def dyn_score_batched(self, ctx: CycleContext, node_requested, extra,
                          feasible, shared):
        if not ctx.snap.has_inter_pod_affinity:
            return None
        state = _affinity_state(ctx, extra)
        cbn = _shared_cbn(ctx, state, shared)
        return interpod_ops.affinity_score_batched(
            ctx.snap, state, ctx.matched_pending, cbn, feasible
        )

    def extra_update_batched(self, ctx: CycleContext, extra, accepted,
                             node_of):
        return _update_affinity_state_batched(
            ctx, self.name, extra, accepted, node_of
        )


class DefaultPreemption(PluginBase):
    """PostFilter: batched what-if preemption (ops/preemption.py).

    Config args: `budget` (candidates prefiltered per cycle, default
    256) and `scan_budget` (nominations per cycle, default 64) — the
    per-cycle latency budgets; pods beyond them retry next cycle."""

    name = "DefaultPreemption"

    def post_filter(self, ctx: CycleContext, assignment, node_requested,
                    gate_rows, excluded=None):
        # preemption_ops is imported at MODULE scope, never from inside
        # this (traced) body: its module-level jnp constants (_BIG_I32)
        # would otherwise be created under the first trace's context,
        # and a later retrace of the same jitted post_filter (e.g. with
        # a CycleDecision instead of a CycleResult) would read them as
        # escaped tracers of a dead trace (UnexpectedTracerError)
        kw = {}
        if "budget" in self.args:
            kw["budget"] = int(self.args["budget"])
        if "scan_budget" in self.args:
            kw["scan_budget"] = int(self.args["scan_budget"])
        return preemption_ops.run_preemption(
            ctx,
            assignment=assignment,
            node_requested=node_requested,
            gate_rows=gate_rows,
            excluded=excluded,
            **kw,
        )


class PodTopologySpread(PluginBase):
    name = "PodTopologySpread"

    def extra_init(self, ctx: CycleContext):
        return _claim_affinity_state(ctx, self.name)

    def dyn_mask(self, ctx: CycleContext, p, node_requested, extra):
        if not ctx.snap.has_topology_spread:
            return None
        return interpod_ops.spread_dyn_mask(
            ctx.snap, _affinity_state(ctx, extra), p
        )

    def dyn_score(self, ctx: CycleContext, p, node_requested, extra, feasible):
        if not ctx.snap.has_topology_spread:
            return None
        return interpod_ops.spread_dyn_score(
            ctx.snap, _affinity_state(ctx, extra), p, feasible
        )

    def extra_update(self, ctx: CycleContext, extra, p, node, committed):
        return _update_affinity_state(ctx, self.name, extra, p, node, committed)

    def dyn_mask_batched(self, ctx: CycleContext, node_requested, extra,
                         shared):
        if not ctx.snap.has_topology_spread:
            return None
        state = _affinity_state(ctx, extra)
        cbn = _shared_cbn(ctx, state, shared)
        if "spread_minc" not in shared:
            shared["spread_minc"] = interpod_ops.spread_minc(ctx.snap, state)
        return interpod_ops.spread_mask_batched(
            ctx.snap, state, cbn, shared["spread_minc"]
        )

    def dyn_score_batched(self, ctx: CycleContext, node_requested, extra,
                          feasible, shared):
        if not ctx.snap.has_topology_spread:
            return None
        state = _affinity_state(ctx, extra)
        cbn = _shared_cbn(ctx, state, shared)
        return interpod_ops.spread_score_batched(
            ctx.snap, state, cbn, feasible
        )

    def extra_update_batched(self, ctx: CycleContext, extra, accepted,
                             node_of):
        return _update_affinity_state_batched(
            ctx, self.name, extra, accepted, node_of
        )
