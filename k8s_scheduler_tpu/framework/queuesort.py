"""QueueSort extension point (SURVEY.md §2 C11).

Upstream's queueSort plugin supplies `Less(podInfo1, podInfo2)` and owns
the activeQ heap ordering; exactly ONE queueSort plugin is enabled per
scheduler, and all profiles must agree on it (the queue is shared). The
default is PrioritySort: priority desc, then creation timestamp asc
(expected `pkg/scheduler/framework/plugins/queuesort/priority_sort.go` —
[UNVERIFIED], mount empty).

TPU-native shape: there is no host-side heap — the encoder bakes the
queue order into the snapshot's `pod_order` rank, which every commit
engine honors (the scan commits in rank order; the rounds engine's
capacity prefix and guard tables arbitrate same-target contention by
rank; the preemption pass fills its candidate window by rank). The
extension point is therefore a batched RANK function consumed at encode
time: `rank(pods, priorities, creation) -> i32 [P]` queue positions.
A comparator-based `Less` would force a host-side O(P log P) Python-
callback sort per cycle; the vectorized key form computes the same
total order in one lexsort.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np


class QueueSortPlugin:
    """Protocol: subclasses order the pending set.

    `rank` returns each pod's queue position (0 = scheduled first) as an
    i32 array over the REAL pods; the encoder places ranks into the
    padded `pod_order` field (pad slots get INT32_MAX)."""

    name = "QueueSort"

    def __init__(self, args: dict | None = None):
        self.args = dict(args or {})

    def rank(
        self,
        pods: Sequence,
        priorities: np.ndarray,  # i32 [P] spec.priority
        creation: np.ndarray,  # f64 [P] creationTimestamp
    ) -> np.ndarray:
        raise TypeError(
            f"{type(self).__name__} must implement rank() "
            "(QueueSortPlugin is a protocol, not a usable plugin)"
        )


def _ranks_from_order(order_key: np.ndarray, n: int) -> np.ndarray:
    out = np.empty(n, np.int32)
    out[order_key] = np.arange(n, dtype=np.int32)
    return out


class PrioritySort(QueueSortPlugin):
    """Default queueSort: priority desc, creation asc, index as the
    final deterministic tie-break (upstream compares pod UIDs last; the
    encode index is this build's stable equivalent)."""

    name = "PrioritySort"

    def rank(self, pods, priorities, creation):
        n = len(pods)
        order_key = np.lexsort(
            (np.arange(n), creation[:n], -priorities[:n])
        )
        return _ranks_from_order(order_key, n)


class CreationSort(QueueSortPlugin):
    """FIFO by creation timestamp, ignoring priority — the classic
    example of a swapped ordering plugin (args: {"newest_first": bool}
    flips to LIFO)."""

    name = "CreationSort"

    def rank(self, pods, priorities, creation):
        n = len(pods)
        c = creation[:n]
        if self.args.get("newest_first"):
            c = -c
        order_key = np.lexsort((np.arange(n), c))
        return _ranks_from_order(order_key, n)


_QUEUE_SORTS: dict[str, type[QueueSortPlugin]] = {
    PrioritySort.name: PrioritySort,
    CreationSort.name: CreationSort,
}


def register_queue_sort(cls: type[QueueSortPlugin]) -> type[QueueSortPlugin]:
    """Register a custom queueSort plugin class (usable as decorator)."""
    _QUEUE_SORTS[cls.name] = cls
    return cls


def make_queue_sort(name: str, args: dict | None = None) -> QueueSortPlugin:
    cls = _QUEUE_SORTS.get(name)
    if cls is None:
        raise KeyError(
            f"unknown queueSort plugin {name!r}; registered: "
            f"{sorted(_QUEUE_SORTS)}"
        )
    return cls(args)


def queue_sort_for_profile(profile) -> QueueSortPlugin:
    """Resolve a config Profile's queueSort plugin. Exactly one is
    active, like upstream: an explicitly ENABLED plugin replaces the
    default outright (no need to also disable PrioritySort — a queue
    cannot follow two orders); otherwise PrioritySort. The scheduler
    cannot run without an order, so disabling everything still falls
    back to PrioritySort."""
    qs = profile.plugins.queue_sort
    name = qs.enabled[0].name if qs.enabled else PrioritySort.name
    return make_queue_sort(name, profile.plugin_config.get(name))
