"""Scheduler-framework extension points, TPU-native shape.

The reference's framework (`framework/runtime/framework.go` — [UNVERIFIED],
mount empty; SURVEY.md §2 C6) runs plugin callbacks per pod per extension
point: PreEnqueue, QueueSort, PreFilter, Filter, PostFilter, PreScore,
Score+NormalizeScore, Reserve, Permit, PreBind, Bind, PostBind.

The TPU-native mapping, per extension point:

- QueueSort        -> the priority-ordered `pod_order` rank (encoder) used
                      by the commit scan; PrioritySort semantics built in.
- PreFilter        -> `CycleContext` precomputes shared across plugins
                      (expression-table node masks etc.), computed ONCE per
                      cycle, batched — the analogue of PreFilter state.
- Filter           -> `static_mask` (batched [P, N], independent of
                      in-cycle commitments) and/or `dyn_mask` ([N] inside
                      the commit scan, sees running state).
- PostFilter       -> `post_filter` (batched preemption, ops/preemption.py).
- PreScore/Score   -> `static_score` / `dyn_score`, each 0..100 per the
                      upstream NormalizeScore contract; the runtime applies
                      the configured integer plugin weight.
- Reserve..PostBind-> host-side (core/scheduler.py, service/): assume,
                      gang Permit, binding. Not device code.

A plugin implements any subset; `None` means "not implemented at this
point". All array-returning hooks are traced inside ONE jit, so plugins
compose into a single fused XLA program — the registry is a program
assembler, not a callback dispatcher.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable

import jax.numpy as jnp

from ..models.encoding import ClusterSnapshot
from ..ops import interpod, labels


class CycleContext:
    """Shared per-cycle precomputes (the PreFilter-state analogue).

    Lazily computed, cached: plugins ask for what they need; anything no
    enabled plugin asks for is never computed (and XLA dead-code-eliminates
    anything unused)."""

    def __init__(self, snap: ClusterSnapshot):
        self.snap = snap
        self._cache: dict[str, Any] = {}

    def get(self, key: str, compute) -> Any:
        if key not in self._cache:
            # a CycleContext lives exactly as long as one trace: the
            # memo is MEANT to be written at trace time (it dedupes
            # recomputation across plugins within the trace) and is
            # garbage the moment tracing ends
            self._cache[key] = compute(self.snap)  # schedlint: disable=JP004 -- per-trace memo; the object dies with the trace
        return self._cache[key]

    @property
    def expr_node_mask(self) -> jnp.ndarray:  # bool [Ex, N]
        return self.get("expr_node_mask", labels.expr_node_mask)

    @property
    def matched_pending(self) -> jnp.ndarray:  # bool [S, P]
        return self.get("matched_pending", interpod.matched_pending)

    @property
    def matched_existing(self) -> jnp.ndarray:  # bool [S, E]
        return self.get("matched_existing", interpod.matched_existing)

    def initial_affinity_state(self):
        return self.get(
            "initial_affinity_state",
            lambda s: interpod.initial_state(s, self.matched_existing),
        )


@runtime_checkable
class Plugin(Protocol):
    """Base protocol. Concrete plugins subclass `PluginBase`."""

    name: str


class PluginBase:
    name: str = ""

    def __init__(self, args: dict | None = None):
        self.args = args or {}

    # --- Filter ---
    def static_mask(self, ctx: CycleContext) -> jnp.ndarray | None:
        return None

    def dyn_mask(self, ctx: CycleContext, p, node_requested, extra) -> jnp.ndarray | None:
        return None

    # --- Score (0..100; runtime applies weight) ---
    def static_score(self, ctx: CycleContext) -> jnp.ndarray | None:
        return None

    def dyn_score(self, ctx: CycleContext, p, node_requested, extra,
                  feasible) -> jnp.ndarray | None:
        """`feasible` is the pod's full feasibility row [N] (static &
        dynamic masks combined) for upstream-style normalize-over-feasible
        scoring."""
        return None

    # --- scan-carried state (running domain counts etc.) ---
    def extra_init(self, ctx: CycleContext) -> Any | None:
        return None

    def extra_update(self, ctx: CycleContext, extra, p, node, committed):
        return extra

    # --- batched dynamic path (round-based commit, ops/rounds.py):
    # whole-pending-set [P, N] evaluation against the current running
    # state, plus a whole-round state fold. A plugin that implements a
    # per-pod dyn hook MUST implement the batched counterpart too —
    # Framework.check_batched_parity() (run when a rounds-mode cycle is
    # built) raises otherwise, because the rounds engine only calls the
    # batched path. ---
    def dyn_mask_batched(self, ctx: CycleContext, node_requested, extra,
                         shared: dict) -> jnp.ndarray | None:
        """`shared` is a per-round trace-time scratch dict: plugins stash
        precomputes derived from the round state there (e.g. the
        counts-by-node table) so co-enabled plugins don't recompute them."""
        return None

    def dyn_score_batched(self, ctx: CycleContext, node_requested, extra,
                          feasible, shared: dict) -> jnp.ndarray | None:
        """`feasible` is the full [P, N] feasibility (static & dynamic)
        for normalize-over-feasible scoring."""
        return None

    def extra_update_batched(self, ctx: CycleContext, extra, accepted,
                             node_of):
        """Fold a round's placements (accepted bool [P], node_of i32 [P])
        into this plugin's extra state."""
        return extra

    def score_node_anchor(self, ctx: CycleContext,
                          node_requested) -> jnp.ndarray | None:
        """Node-local component of this plugin's dynamic score at the
        given node_requested (f32 [N]), or None if the score has no such
        component. The rounds engine adds (anchor(now) - anchor(round
        start)) to stale claim scores between acceptance passes so a node
        that fills up loses attractiveness immediately — the batched
        analogue of sequential scheduling's per-pod score freshness. Used
        ONLY for claim ordering; masks and reported scores are
        unaffected."""
        return None

    # --- PostFilter (preemption): runs after the commit scan over the
    # pods that found no node; returns a PreemptionResult or None.
    # `excluded` [P] marks pods that must not preempt (gang-dropped) ---
    def post_filter(self, ctx: CycleContext, assignment, node_requested,
                    gate_rows, excluded=None):
        return None
