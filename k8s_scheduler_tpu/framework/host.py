"""Host-side extension points: Reserve / Permit / PreBind / PostBind, and
the HTTP scheduler-extender client (SURVEY.md §2 C10).

The device program owns the batched Filter/Score/commit; everything that
upstream runs BETWEEN selecting a host and posting the Binding — Reserve,
Permit, PreBind, Bind, PostBind — is host-side control flow around
assume/bind, so the extension surface lives here as plain Python hooks the
`Scheduler` invokes per scheduled pod (core/scheduler.py apply loop).
Out-of-tree code registers a `HostPlugin`; any hook returning a rejection
string vetoes the placement (Reserve/Permit reject -> unreserve + requeue
unschedulable with the plugin as the reason; PreBind error -> unreserve +
backoff retry, upstream RunPreBindPlugins semantics).

`HTTPExtender` speaks the upstream SchedulerExtender webhook protocol
(ExtenderArgs/ExtenderFilterResult/HostPriorityList JSON): Filter and
Prioritize run host-side BEFORE the device cycle (their verdicts ride into
the device program as an extra [P, N] mask / score table), and a bind-verb
extender replaces the default binder for pods it manages.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Sequence

from ..config.types import Extender
from ..models.api import Node, Pod


class HostPlugin:
    """Base class for host-side plugins; override any subset."""

    name: str = ""

    def reserve(self, pod: Pod, node_name: str) -> str | None:
        """Claim host-side resources for a tentative placement. A string
        return rejects the placement (the reason)."""
        return None

    def unreserve(self, pod: Pod, node_name: str) -> None:
        """Roll back reserve() — called on any later rejection/failure."""

    def permit(self, pod: Pod, node_name: str) -> str | None:
        """Final veto before binding (upstream Permit; the batched gang
        unwind already handles Coscheduling on-device)."""
        return None

    def pre_bind(self, pod: Pod, node_name: str) -> str | None:
        """Pre-bind work (e.g. volume attach). A string return fails the
        bind; the pod retries with backoff."""
        return None

    def post_bind(self, pod: Pod, node_name: str) -> None:
        """Informational; runs after a successful bind."""


class HostPluginRejection(Exception):
    def __init__(self, plugin: str, point: str, reason: str):
        super().__init__(f"{plugin}/{point}: {reason}")
        self.plugin = plugin
        self.point = point
        self.reason = reason


def run_reserve_permit_prebind(
    plugins: Sequence[HostPlugin], pod: Pod, node_name: str
) -> None:
    """Reserve -> Permit -> PreBind across `plugins`, unreserving already-
    reserved plugins (reverse order) on any rejection. Raises
    HostPluginRejection; the caller maps the point to requeue semantics."""
    reserved: list[HostPlugin] = []

    def unwind() -> None:
        for p in reversed(reserved):
            p.unreserve(pod, node_name)

    for p in plugins:
        r = p.reserve(pod, node_name)
        if r is not None:
            unwind()
            raise HostPluginRejection(p.name, "Reserve", r)
        reserved.append(p)
    for p in plugins:
        r = p.permit(pod, node_name)
        if r is not None:
            unwind()
            raise HostPluginRejection(p.name, "Permit", r)
    for p in plugins:
        r = p.pre_bind(pod, node_name)
        if r is not None:
            unwind()
            raise HostPluginRejection(p.name, "PreBind", r)


def run_post_bind(
    plugins: Sequence[HostPlugin], pod: Pod, node_name: str
) -> None:
    for p in plugins:
        p.post_bind(pod, node_name)


def run_unreserve(
    plugins: Sequence[HostPlugin], pod: Pod, node_name: str
) -> None:
    for p in reversed(list(plugins)):
        p.unreserve(pod, node_name)


# ---------------------------------------------------------------------------
# HTTP extenders
# ---------------------------------------------------------------------------


class ExtenderError(Exception):
    pass


def _pod_json(pod: Pod) -> dict:
    return {
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "uid": pod.uid,
            "labels": dict(pod.metadata.labels),
        },
    }


class HTTPExtender:
    """Upstream SchedulerExtender webhook client (JSON over HTTP)."""

    def __init__(self, config: Extender):
        self.config = config

    def _post(self, verb: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self.config.url_prefix.rstrip('/')}/{verb}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.config.http_timeout_seconds
            ) as resp:
                return json.loads(resp.read() or b"{}")
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            raise ExtenderError(str(e)) from e

    def filter(self, pod: Pod, node_names: list[str]) -> list[str]:
        """Feasible subset of `node_names` for `pod` (ExtenderFilterResult;
        raises ExtenderError on webhook failure or Error payload)."""
        out = self._post(
            self.config.filter_verb,
            {"Pod": _pod_json(pod), "NodeNames": node_names},
        )
        if out.get("Error"):
            raise ExtenderError(out["Error"])
        names = out.get("NodeNames")
        return list(names) if names is not None else list(node_names)

    def prioritize(self, pod: Pod, node_names: list[str]) -> dict[str, float]:
        """node name -> weighted score (HostPriorityList x weight)."""
        out = self._post(
            self.config.prioritize_verb,
            {"Pod": _pod_json(pod), "NodeNames": node_names},
        )
        if isinstance(out, dict):
            items = out.get("Items") or out.get("items") or []
        else:
            items = out
        return {
            h["Host"]: float(h["Score"]) * self.config.weight for h in items
        }

    def bind(self, pod: Pod, node_name: str) -> None:
        out = self._post(
            self.config.bind_verb,
            {
                "PodName": pod.name,
                "PodNamespace": pod.namespace,
                "PodUID": pod.uid,
                "Node": node_name,
            },
        )
        if out.get("Error"):
            raise ExtenderError(out["Error"])

    @property
    def is_filter(self) -> bool:
        return bool(self.config.filter_verb)

    @property
    def is_prioritizer(self) -> bool:
        return bool(self.config.prioritize_verb)

    @property
    def is_binder(self) -> bool:
        return bool(self.config.bind_verb)


def run_extender_prepass(
    extenders: Sequence[HTTPExtender],
    pending: Sequence[Pod],
    nodes: Sequence[Node],
):
    """Filter+Prioritize every pending pod through every configured
    extender. Returns (mask [P, N] bool, score [P, N] f32, errors
    dict pod-index -> message) as numpy arrays, or (None, None, {}) when
    no extender filters or prioritizes."""
    import numpy as np

    from concurrent.futures import ThreadPoolExecutor

    flt = [e for e in extenders if e.is_filter]
    pri = [e for e in extenders if e.is_prioritizer]
    if not flt and not pri:
        return None, None, {}
    names = [n.name for n in nodes]
    index = {nm: i for i, nm in enumerate(names)}
    P, N = len(pending), len(nodes)
    mask = np.ones((P, N), bool)
    score = np.zeros((P, N), np.float32)
    errors: dict[int, str] = {}

    def one_pod(pi_pod):
        pi, pod = pi_pod
        feasible = names
        err_msg = None
        for e in flt:
            try:
                feasible = e.filter(pod, list(feasible))
            except ExtenderError as err:
                if e.config.ignorable:
                    continue
                err_msg = str(err)
                feasible = []
                break
        row = np.zeros(N, bool)
        for nm in feasible:
            i = index.get(nm)
            if i is not None:
                row[i] = True
        srow = np.zeros(N, np.float32)
        if err_msg is None:
            for e in pri:
                try:
                    for nm, s in e.prioritize(pod, list(feasible)).items():
                        i = index.get(nm)
                        if i is not None:
                            srow[i] += s
                except ExtenderError as err:
                    if e.config.ignorable:
                        continue  # consult the remaining extenders
                    err_msg = str(err)
                    row[:] = False
                    break
        return pi, row, srow, err_msg

    # webhook round-trips are independent per pod; a bounded pool keeps a
    # slow/down extender from serializing the whole pending set behind
    # per-pod timeouts
    with ThreadPoolExecutor(max_workers=16) as pool:
        for pi, row, srow, err_msg in pool.map(
            one_pod, enumerate(pending)
        ):
            mask[pi] = row
            score[pi] = srow
            if err_msg is not None:
                errors[pi] = err_msg
    return mask, score, errors
