"""Plugin registry: name -> factory, the analogue of the reference's
`runtime.Registry` (SURVEY.md §2 C6 — [UNVERIFIED], mount empty).
Out-of-tree plugins register the same way the defaults do."""

from __future__ import annotations

from typing import Callable

from .interfaces import PluginBase

Factory = Callable[[dict], PluginBase]


class Registry:
    def __init__(self) -> None:
        self._factories: dict[str, Factory] = {}

    def register(self, name: str, factory: Factory) -> None:
        if name in self._factories:
            raise ValueError(f"plugin {name!r} already registered")
        self._factories[name] = factory

    def make(self, name: str, args: dict | None = None) -> PluginBase:
        if name not in self._factories:
            raise KeyError(f"unknown plugin {name!r}; registered: "
                           f"{sorted(self._factories)}")
        return self._factories[name](args or {})

    def names(self) -> list[str]:
        return sorted(self._factories)


def default_registry() -> Registry:
    from . import plugins as p

    r = Registry()
    for cls in (
        p.NodeUnschedulable,
        p.NodeName,
        p.NodePorts,
        p.NodeResourcesFit,
        p.NodeResourcesBalancedAllocation,
        p.VolumeBinding,
        p.NodeAffinity,
        p.TaintToleration,
        p.ImageLocality,
        p.InterPodAffinity,
        p.PodTopologySpread,
        p.DefaultPreemption,
    ):
        r.register(cls.name, lambda args, _cls=cls: _cls(args))
    return r
