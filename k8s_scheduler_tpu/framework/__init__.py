from .interfaces import CycleContext, Plugin  # noqa: F401
from .registry import Registry, default_registry  # noqa: F401
from .runtime import Framework  # noqa: F401
