#!/usr/bin/env python
"""scheduler_perf-style benchmark suite: the five BASELINE configs with
feature-realistic synthetic workloads and latency percentiles.

The model is upstream's `test/integration/scheduler_perf/` (SURVEY.md §4,
§7 step 8): drive thousands of synthetic pods/nodes through the scheduler
and record throughput plus latency percentiles. Each config here runs
`BENCH_SNAPSHOTS` DISTINCT snapshots (pending pods re-drawn per cycle, so
jit-cache behaviour is what steady serving sees) through the fused cycle —
plus, for config #4, the PostFilter/preemption pass whenever pods are left
unschedulable, and for config #5, gang all-or-nothing unwinds.

Emits one JSON line per config:
    {"config": 4, "name": "full_default_preemption", "decisions_per_sec":…,
     "p50_ms":…, "p99_ms":…, "scheduled":…, "preemptors":…, …}

Used by bench.py (which reports the driver's single headline line) and
runnable standalone:  BENCH_SNAPSHOTS=10 python bench_suite.py 1 4
"""

from __future__ import annotations

import collections
import json
import os
import sys
import time


def _percentile(xs: list[float], q: float) -> float:
    ys = sorted(xs)
    if not ys:
        return 0.0
    k = min(len(ys) - 1, max(0, int(round(q / 100.0 * (len(ys) - 1)))))
    return ys[k]


def _pad(n: int, b: int = 128) -> int:
    return ((n + b - 1) // b) * b


def make_config_base(cfg: int):
    """(nodes, existing, groups_unused) — the STABLE cluster for `cfg`,
    generated once per run: in steady serving the node and running-pod
    objects persist across cycles (the scheduler's cache holds them), so
    the encoder's per-object row cache applies; only the pending set is
    fresh each cycle."""
    nodes, _pods, existing, _groups = make_config_workload(cfg, seed=0)
    return nodes, existing


# per-config pending-pod distribution (kwargs for synth.make_pods)
PENDING_PARAMS = {
    1: dict(),
    2: dict(selector_fraction=0.5, toleration_fraction=0.4),
    3: dict(affinity_fraction=0.3, anti_affinity_fraction=0.2,
            spread_fraction=0.2, num_apps=500),
    4: dict(affinity_fraction=0.3, anti_affinity_fraction=0.2,
            spread_fraction=0.2, selector_fraction=0.3,
            toleration_fraction=0.1, priorities=(0, 0, 10, 100),
            num_apps=500),
}


def make_config_pending(cfg: int, seed: int, count: int | None = None,
                        name_prefix: str = "pod"):
    """(pending, groups) for config `cfg` — only the pending side, so the
    per-snapshot redraw doesn't rebuild the whole cluster."""
    from k8s_scheduler_tpu.utils.synth import make_gang_pods, make_pods

    if cfg == 5:  # gang-schedule 1k 8-replica jobs on 2k nodes
        # capacity below aggregate demand: the tail of the priority order
        # cannot fully place, so all-or-nothing unwinds really fire
        return make_gang_pods(1000, replicas=8, seed=seed)
    n = count if count is not None else CONFIG_SHAPES[cfg][0]
    return (
        make_pods(n, seed=seed, name_prefix=name_prefix,
                  **PENDING_PARAMS[cfg]),
        [],
    )


def make_config_workload(cfg: int, seed: int):
    """(nodes, pending, existing, groups) for BASELINE config `cfg`; `seed`
    re-draws the pending set so every snapshot is distinct."""
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    pods, groups = make_config_pending(cfg, seed)
    if cfg == 1:  # 100 pods x 10 nodes, CPU/mem requests only
        return make_cluster(10, with_labels=False), pods, [], []
    if cfg == 2:  # 1k pods x 100 nodes, node-affinity + taints/tolerations
        return make_cluster(100, taint_fraction=0.3), pods, [], []
    if cfg == 3:  # 5k pods x 1k nodes, inter-pod (anti-)affinity
        return make_cluster(1000), pods, [], []
    if cfg == 4:  # 10k pods x 5k nodes, full default plugin set + preemption
        # small nodes + a low-priority existing workload occupying most
        # capacity: high-priority pending pods must preempt, low-priority
        # ones go unschedulable — the PostFilter pass has real work
        nodes = make_cluster(5000, taint_fraction=0.1, cpu_choices=(4, 8, 16))
        existing_pods = make_pods(
            12000,
            seed=991,  # fixed: the running cluster is stable across cycles
            name_prefix="run",
            affinity_fraction=0.1,
            spread_fraction=0.1,
            num_apps=500,
        )
        existing = [
            (p, f"node-{i % 5000}") for i, p in enumerate(existing_pods)
        ]
        return nodes, pods, existing, []
    if cfg == 5:
        return make_cluster(2000, cpu_choices=(8,)), pods, [], groups
    raise ValueError(f"unknown config {cfg}")


CONFIG_NAMES = {
    1: "resources_only",
    2: "labels_taints",
    3: "interpod_affinity",
    4: "full_default_preemption",
    5: "gang_coscheduling",
    # sharded multi-chip scale sweep (ISSUE 10 / ROADMAP item 3): the
    # carry cycle over device counts {1,2,4,8} at grid points up to
    # 100k pods x 50k nodes, reporting per-device ms, compiled
    # collective payload MB/cycle, and scaling efficiency — config 8
    # below (CONFIG_SHAPES holds the target headline geometry; points
    # the host cannot hold are skipped LOUDLY, never silently)
    8: "sharded_scale",
    # compile-regime churn soak (ISSUE 8 / ROADMAP item 2): the pending
    # count oscillates across a P pad-bucket boundary through a REAL
    # Scheduler, measuring regime flips, compile-attributed stall
    # cycles, and the persistent executable cache's warm-vs-cold cost
    6: "regime_churn",
    # fault-storm soak (ISSUE 9): a scripted FaultPlan fires a hung
    # fetch (longer than the dispatch deadline) and every device-error
    # marker class through a REAL Scheduler, measuring MTTR (wall ms
    # from leaving rung 0 to returning), degraded cycles, and the
    # watchdog's bound on the hang cycle — gated by bench_diff
    7: "fault_storm",
    # submission front door (ISSUE 14 / ROADMAP item 1): an open-loop
    # (arrival-rate-driven) load drive through the REAL admission API —
    # sustained phase holds p99 submit->bind with zero shed, a 2x-
    # capacity overload phase must shed with RESOURCE_EXHAUSTED while
    # queue depth stays bounded and every ACKED pod still binds exactly
    # once — gated directionally by bench_diff (submit p99 rise / shed
    # rate rise = regressed)
    9: "front_door",
    # admission-time incremental encode (ISSUE 16): the SAME open-loop
    # front-door drive with incrementalEncode off (rebuild baseline),
    # on, and on at a DOUBLED arrival rate — reporting how much encode
    # host time hides in the ack path's shadow (encode_hidden_pct),
    # the O(1)-finalize flush cost (finalize_p50_ms, flush rate,
    # speedup vs the rebuild baseline), and whether submit->bind p50
    # stays flat as the arrival rate doubles — gated by bench_diff
    # (--max-finalize-rise / --min-encode-hidden)
    10: "host_encode",
}
CONFIG_SHAPES = {1: (100, 10), 2: (1000, 100), 3: (5000, 1000),
                 4: (10000, 5000), 5: (8000, 2000), 6: (80, 16),
                 7: (48, 16), 8: (100000, 50000), 9: (0, 16),
                 10: (0, 16)}


def _draw_pending(cfg: int, i: int, prev: list | None, churn: float):
    """Snapshot i's pending set: `churn` of the pods are fresh arrivals
    (distinct names per snapshot), the rest carry over from the previous
    snapshot (same objects — what a scheduler's queue holds between
    cycles). Gang configs redraw whole snapshots so group membership
    stays coherent."""
    import numpy as np

    if prev is not None and churn <= 0.0:
        # fully-warm steady state: every pending object carries over
        if cfg == 5:
            from k8s_scheduler_tpu.models.api import PodGroup

            return prev, [PodGroup(f"job-{g}", 8)
                          for g in range(len(prev) // 8)]
        return prev, []
    if cfg == 5 and prev is not None and churn < 1.0:
        # gang churn happens at JOB granularity: whole 8-replica jobs are
        # redrawn (fresh objects, same job names/min_member) so group
        # membership stays coherent while the row cache sees a realistic
        # carry-over
        from k8s_scheduler_tpu.models import MakePod

        R = 8
        G = len(prev) // R
        k = max(1, int(G * churn))
        rng = np.random.default_rng(7000 + i)
        out = list(prev)
        for g in rng.choice(G, size=k, replace=False):
            for r in range(R):
                out[g * R + r] = (
                    MakePod(f"job-{g}-{r}")
                    .req({"cpu": f"{int(rng.integers(2, 8)) * 500}m",
                          "memory": "1Gi"})
                    .group(f"job-{g}")
                    .created(float(g * R + r))
                    .obj()
                )
        from k8s_scheduler_tpu.models.api import PodGroup

        return out, [PodGroup(f"job-{g}", R) for g in range(G)]
    if prev is None or churn >= 1.0:
        pods, groups = make_config_pending(cfg, seed=1000 + i)
        return pods, groups
    k = max(1, int(len(prev) * churn))
    fresh, groups = make_config_pending(
        cfg, seed=1000 + i, count=k, name_prefix=f"pod{i}-"
    )
    rng = np.random.default_rng(7000 + i)
    idx = rng.choice(len(prev), size=k, replace=False)
    out = list(prev)
    for j, src in zip(idx, fresh):
        out[j] = src
    return out, groups


def _parse_multi_k_env() -> "list[int]":
    """Parse BENCH_MULTI_K ("1,4,8,16"; "1" or empty disables). Raises
    a named error on a typo — callers invoke this BEFORE the timed
    measurement loop so a malformed value cannot throw away minutes of
    completed device time at artifact-assembly."""
    mk_env = os.environ.get("BENCH_MULTI_K", "")
    if not mk_env:
        return []
    try:
        ks = sorted(
            {max(int(x), 1) for x in mk_env.split(",") if x.strip()}
        )
    except ValueError as e:
        raise SystemExit(
            f"BENCH_MULTI_K={mk_env!r} is not a comma list of ints: {e}"
        ) from None
    if not ks or ks == [1]:
        # "1" disables as documented — a K=[1] "sweep" would emit
        # tunnel_amortization=1.0 and trip bench_diff's amortization
        # tripwire against a real-sweep baseline
        return []
    if 1 not in ks:
        ks = [1] + ks  # the sweep needs its own baseline
    return ks


def run_config(cfg: int, snapshots: int = 50) -> dict:
    if cfg == 6:
        return run_regime_churn_config(snapshots=snapshots)
    if cfg == 7:
        return run_fault_storm_config(snapshots=snapshots)
    if cfg == 8:
        return run_sharded_scale_config(snapshots=snapshots)
    if cfg == 9:
        return run_front_door_config(snapshots=snapshots)
    if cfg == 10:
        return run_host_encode_config(snapshots=snapshots)
    if cfg == 11:
        return run_tenant_arena_config(snapshots=snapshots)
    import jax
    import numpy as np

    from k8s_scheduler_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    multi_ks = _parse_multi_k_env()  # fail fast on a typo'd env var

    from k8s_scheduler_tpu.models import SnapshotEncoder

    from k8s_scheduler_tpu.core import (
        build_carry_fns,
        build_diagnosis_fn,
        build_packed_cycle_carry_fn,
        build_packed_cycle_fn,
        build_packed_preemption_fn,
        build_stable_state_fn,
    )
    from k8s_scheduler_tpu.models import packing

    P_real, N_real = CONFIG_SHAPES[cfg]
    # the round-based batched commit is the production engine; the strict
    # sequential scan is available for comparison via BENCH_COMMIT_MODE
    mode = os.environ.get("BENCH_COMMIT_MODE", "rounds")
    # carry mode (default for rounds): the [P,N] static base and [S,P]
    # matched-pending live on device across cycles; each cycle updates
    # only the encoder-reported dirty rows, and FailedScheduling
    # attribution runs in the separate diagnosis program off the
    # decision path
    use_carry = mode == "rounds" and os.environ.get("BENCH_CARRY", "1") == "1"
    churn = float(os.environ.get("BENCH_CHURN", 0.2))
    # the packed path ships 2 input buffers per cycle instead of ~80 (a
    # fresh buffer pays a large first-use overhead through the tunnel);
    # compiled programs memoize per spec regime so the throughput loop
    # (which replays the same snapshot sequence) never compiles inside
    # its timed window
    spec = None
    cycle = preempt = None
    packed_memo: dict = {}

    def packed_fns(sp):
        key = sp.key()
        hit = packed_memo.get(key)
        if hit is None:
            if use_carry:
                from k8s_scheduler_tpu.core.cycle import CarryKeeper

                cyc = build_packed_cycle_carry_fn(sp)
                keeper = CarryKeeper(sp)
                diag = build_diagnosis_fn(sp)
            else:
                cyc = build_packed_cycle_fn(sp, commit_mode=mode)
                keeper = diag = None
            hit = (
                cyc,
                build_packed_preemption_fn(sp) if cfg == 4 else None,
                build_stable_state_fn(sp),
                keeper, diag,
            )
            packed_memo[key] = hit
        return hit

    stable_memo: dict = {}

    def stable_state(sp, stable_fn, w, b):
        # device-resident stable-side precomputes, rerun only when the
        # encoder's stable side or the spec regime changes
        key = (sp.key(), getattr(enc, "_stable_key", None))
        hit = stable_memo.get(key)
        if hit is None:
            hit = stable_fn(w, b)
            stable_memo.clear()
            stable_memo[key] = hit
        return hit

    # one encoder across snapshots keeps the string/selector dictionaries
    # stable (what a long-lived serving process sees). pad_existing
    # pre-sizes the sticky E regime for the fold loop's growth (base +
    # up to one full pending set before the first eviction + churn-sized
    # binds for the rest of the window): an E-regime flip mid-run costs
    # a full recompile AND has tripped a rig executable-cache wedge
    # (see bench.py _run_one_isolated).
    fold_binds = (
        os.environ.get("BENCH_FOLD", "1") == "1" and cfg != 5
    )
    fold_evict_every = int(os.environ.get("BENCH_FOLD_EVICT", "4"))
    base_nodes, base_existing = make_config_base(cfg)
    e_need = (
        len(base_existing)
        + P_real
        + (fold_evict_every - 1) * max(1, int(churn * P_real))
    )
    # MPN (hot-node victim-table depth): base depth + the fold window's
    # binds assuming a 4x concentration over the uniform share
    mpn_need = (
        -(-len(base_existing) // max(N_real, 1))
        + 4 * max(1, e_need // max(N_real, 1))
    )
    enc = SnapshotEncoder(
        pad_pods=_pad(P_real), pad_nodes=_pad(N_real),
        pad_existing=_pad(e_need) if fold_binds else None,
        pad_pods_per_node=(
            ((mpn_need + 7) // 8) * 8 if fold_binds else None
        ),
    )

    # Timing methodology: on this rig the TPU sits behind a tunnel with a
    # measured fixed dispatch round-trip (reported as tunnel_rt_ms), and
    # async dispatch reports readiness optimistically — block_until_ready
    # alone massively under-reports. Latency (p50/p99) is measured
    # FORCED-SYNC: each cycle ends with a device->host read, so it
    # includes one tunnel round-trip, exactly what a caller waiting on
    # bindings would see. Throughput (decisions_per_sec, pipelined_ms) is
    # measured over the same snapshots WITHOUT per-cycle forcing: the
    # host encodes snapshot i+1 while the device runs cycle i (JAX async
    # dispatch), one force at the end — how a production driver runs.
    times: list[float] = []
    encode_times: list[float] = []
    compile_s = 0.0
    shape_keys: set = set()
    totals = {"scheduled": 0, "unschedulable": 0, "gang_dropped": 0,
              "preemptors": 0, "victims": 0}
    noop = jax.jit(lambda w: w[:8].sum())
    # journaling overhead measurement (ISSUE 3 acceptance: cycle p50
    # with journaling enabled regresses <5% vs disabled): when
    # BENCH_STATE_DIR is set, every timed latency cycle ALSO emits the
    # write-ahead records the production driver would — one q.pop plus
    # a c.assume/c.finish_binding pair per bound pod — through the real
    # Journal append path (buffered; the group fsync stays on the
    # writer thread, never in the timed window).
    journal = None
    journal_appends = 0
    state_dir = os.environ.get("BENCH_STATE_DIR", "")
    if state_dir:
        from k8s_scheduler_tpu.state import Journal
        from k8s_scheduler_tpu.state.codec import pod_to_state

        journal = Journal(
            os.path.join(state_dir, f"cfg{cfg}-{mode}")
        )
    # output-transfer slimming (core/pipeline.py): the per-cycle forced
    # decision fetch moves an i16 assignment + u8 flag byte per pod
    # instead of i32 + 2 bools — the same payload the serving pipeline
    # blocks on
    from k8s_scheduler_tpu.core import build_decision_slim_fn

    slim = None
    fetch_bytes = 0

    def dispatch(fns, w, b, dirty):
        """Dispatch one decision cycle (carry update + cycle [+ chained
        preemption]) and return (out, pre, diag_fn, stable, wD, bD) —
        the last two being the device-resident packed buffers for
        follow-up programs (diagnosis).

        The packed buffers upload ONCE per cycle via device_put (which
        copies the host arena synchronously, so the next encode may
        mutate it): passing numpy args instead re-uploads 8MB per
        PROGRAM call, measured ~600ms/cycle of tunnel time across the
        4-program chain."""
        cyc, pre_fn, stable_fn, keeper, diag = fns
        w = jax.device_put(w)
        b = jax.device_put(b)
        stable = stable_state(spec, stable_fn, w, b)
        if keeper is not None:
            # _carry_key excludes the existing set: a bind-fold keeps the
            # [P,N] carry valid (st identity also joins the key; the fold
            # mutates st in place, any other stable change rebuilds it)
            enc_st = getattr(enc, "_stable", None)
            carry = keeper.state(
                w, b, stable, dirty,
                (
                    spec.key(), id(enc_st),
                    getattr(enc, "_carry_key", None),
                ),
                pin=enc_st,
            )
            out = cyc(w, b, stable, carry)
        else:
            out = cyc(w, b, stable)
        pre = pre_fn(w, b, out, stable) if pre_fn is not None else None
        return out, pre, diag, stable, w, b

    # ---- bind folding (VERDICT r4 weak #3 / item 3) ----
    # The LATENCY loop models the production steady state: each cycle's
    # bindings fold into the existing set (the encoder's incremental
    # existing-fold keeps the stable side + device carry warm), bound
    # pods leave pending, fresh arrivals refill to P_real, and every
    # FOLD_EVICT_EVERY-th cycle a completion batch removes the folded
    # tail (incremental un-fold). The THROUGHPUT loop below keeps the
    # existing set fixed on purpose — its no-per-cycle-force methodology
    # cannot observe bindings without paying a tunnel round-trip per
    # cycle, so it measures pure decision throughput; the fold cost is
    # carried by p50/p99/encode_p50 here. BENCH_FOLD=0 restores the
    # round-4 fixed-existing behavior. (fold_binds/fold_evict_every are
    # defined above, before the encoder, to size pad_existing.)
    base_len = len(base_existing)
    folded_n = 0
    fold_skipped = 0

    pending = None
    first_bufs = None
    fns = None
    for i in range(snapshots):
        if fold_binds and pending is not None:
            groups = []  # pending was updated in place after the last cycle
        else:
            pending, groups = _draw_pending(cfg, i, pending, churn)
        t0 = time.perf_counter()
        # encode_packed: the delta-arena fast path (encode + pack in one;
        # warm cycles rewrite only churned pod rows of the packed buffers)
        wbuf, bbuf, s2, vsnap, dirty = enc.encode_packed(
            base_nodes, pending, base_existing, groups
        )
        if spec is None or s2.key() != spec.key():
            # new padded-shape/dictionary regime: (re)build + compile
            # (warmup, untimed as cycle latency — reported separately)
            spec = s2
            fns = packed_fns(spec)
            encode_times.append(time.perf_counter() - t0)
            shape_keys.add(spec.key())
            t0 = time.perf_counter()
            if use_carry:
                # compile BOTH carry programs outside the timed window
                keeper = fns[3]
                st0 = stable_state(spec, fns[2], wbuf, bbuf)
                keeper.warm(wbuf, bbuf, st0)
            out, pre, diag, stable, wD, bD = dispatch(
                fns, wbuf, bbuf, dirty
            )
            np.asarray(out.assignment)
            # (re)build + warm the slim-fetch program for this regime's
            # node axis, outside the timed window
            slim = build_decision_slim_fn(out.node_requested.shape[0])
            jax.device_get(
                slim(out.assignment, out.unschedulable, out.gang_dropped)
            )
            if pre is not None:
                np.asarray(pre.nominated)
            if diag is not None:
                np.asarray(
                    diag(wD, bD, stable, out.assignment,
                         out.node_requested, out.pv_claimed)
                )
            compile_s += time.perf_counter() - t0
            dirty = np.empty(0, np.int32)  # carry already current
        else:
            encode_times.append(time.perf_counter() - t0)
        if first_bufs is None:
            first_bufs = (wbuf, bbuf)
        t0 = time.perf_counter()
        out, pre, diag, stable, wD, bD = dispatch(
            fns, wbuf, bbuf, dirty
        )
        # ONE forced fetch of the SLIMMED decision payload — everything
        # the driver needs before binds (each separate np.asarray pays a
        # full tunnel round trip; the flags byte also carries what the
        # totals below used to fetch as two extra bool arrays)
        sa, sflags = slim(
            out.assignment, out.unschedulable, out.gang_dropped
        )
        if pre is not None:
            a16, flags, _nom = jax.device_get((sa, sflags, pre.nominated))
        else:
            a16, flags = jax.device_get((sa, sflags))
        if journal is not None:
            # the driver-shaped emission for this cycle, inside the
            # timed window on purpose: this is the append-path overhead
            # the <5% p50 criterion bounds (no fsync happens here)
            tm = time.monotonic()
            journal.append("q.pop", tm, {})
            journal_appends += 1
            for j in np.flatnonzero(a16[: len(pending)] >= 0):
                p = pending[int(j)]
                journal.append(
                    "c.assume", tm,
                    {"pod": pod_to_state(p),
                     "node": base_nodes[int(a16[int(j)])].name},
                )
                journal.append("c.finish_binding", tm, {"uid": p.uid})
                journal_appends += 2
        times.append(time.perf_counter() - t0)
        a = a16.astype(np.int32)
        fetch_bytes = int(a16.nbytes + flags.nbytes)
        if diag is not None:
            # FailedScheduling attribution runs OFF the decision path:
            # dispatched after decisions are read, overlapping the next
            # snapshot's host-side encode (forced at loop end)
            last_diag = diag(wD, bD, stable, out.assignment,
                             out.node_requested, out.pv_claimed)
        if os.environ.get("BENCH_DEBUG"):
            print(f"  iter={i} cycle={times[-1]:.4f}s", flush=True)

        valid = np.asarray(vsnap.pod_valid)
        totals["scheduled"] += int(((a >= 0) & valid).sum())
        totals["unschedulable"] += int(((flags & 1) != 0).sum())
        totals["gang_dropped"] += int(((flags & 2) != 0).sum())
        if pre is not None and totals["unschedulable"]:
            totals["preemptors"] += int(np.asarray(pre.num_preemptors))
            totals["victims"] += int(np.asarray(pre.victims).sum())

        if fold_binds:
            # bound pods fold into the existing set (the encoder's
            # incremental append-fold); fresh arrivals take their QUEUE
            # SLOTS in place — a slot-reuse driver, so the delta encoder's
            # dirty set is exactly the arrival count, as in r4's churn
            # model, while the stable side now pays the real fold cost
            bidx = np.flatnonzero((a[: len(pending)] >= 0)
                                  & valid[: len(pending)])
            # deterministic pad safety: the e_need model budgets
            # churn-sized binds after the first cycle, but a bind storm
            # can approach P_real per cycle while capacity lasts —
            # folding past the pre-sized E pad would flip the regime
            # mid-run (the wedge pre-sizing avoids), so over-budget
            # folds are skipped for the window and counted
            if bidx.size and len(base_existing) + bidx.size > e_need:
                fold_skipped += int(bidx.size)
                bidx = bidx[:0]
            if bidx.size:
                pending = list(pending)
                arrivals, _g = make_config_pending(
                    cfg, seed=1000 + i, count=int(bidx.size),
                    name_prefix=f"pod{i}-",
                )
                for j, newp in zip(bidx, arrivals):
                    base_existing.append(
                        (pending[int(j)], base_nodes[int(a[int(j)])].name)
                    )
                    pending[int(j)] = newp
                folded_n += int(bidx.size)
            if (i + 1) % fold_evict_every == 0 and folded_n:
                # completion batch: the folded tail finishes and leaves
                # (the encoder's incremental tail un-fold)
                del base_existing[base_len:]
                folded_n = 0

    # fixed tunnel round-trip: a no-op program on DEVICE-RESIDENT data
    # (numpy args would re-upload the 8MB buffer per call and pollute the
    # fixed-cost estimate). Sampled several times so the artifact also
    # carries the round-trip TAIL (tunnel_rt_p99_ms): the rig's stall
    # class lives in exactly this path, and a single draw can land on a
    # stall (or miss one) and skew every derived device_ms number. The
    # fixed-cost estimate below uses the MEDIAN sample — robust to one
    # stalled draw where the old single draw was not.
    dev_w = jax.device_put(first_bufs[0])
    np.asarray(noop(dev_w))
    tunnel_samples = []
    for _ in range(8):
        t0 = time.perf_counter()
        np.asarray(noop(dev_w))
        tunnel_samples.append(time.perf_counter() - t0)
    tunnel_rt = _percentile(tunnel_samples, 50)
    tunnel_rt_p99 = _percentile(tunnel_samples, 99)

    # the throughput loop measures pure decision throughput over a FIXED
    # existing set (see fold note above): drop any folded residue first
    if fold_binds and len(base_existing) > base_len:
        del base_existing[base_len:]

    # pipelined throughput: re-encode + dispatch every snapshot
    # back-to-back, force once — encode overlaps device compute. The
    # pending objects are fresh instances (cold row-cache entries for the
    # churned fraction), the same steady-state the latency loop saw.
    # Snapshot GENERATION is bench fixture work (~150ms/draw at config
    # #4 — synthetic pod construction, not the system under test), so
    # the whole sequence is drawn before the timed window.
    pending = None
    drawn = []
    for i in range(snapshots):
        pending, groups = _draw_pending(cfg, i, pending, churn)
        drawn.append((list(pending), groups))
    last = None
    t0 = time.perf_counter()
    for pending, groups in drawn:
        wbuf, bbuf, s3, _vsnap, dirty = enc.encode_packed(
            base_nodes, pending, base_existing, groups
        )
        if s3.key() != spec.key():
            # regime change mid-loop: memo hit for regimes the latency
            # loop already compiled (the sequence replays); a genuinely
            # new regime would compile here and pollute the window, but
            # grow-only dims make that a one-off
            spec = s3
            fns = packed_fns(spec)
        out, out_pre, diag, stable, wD, bD = dispatch(
            fns, wbuf, bbuf, dirty
        )
        if diag is not None:
            diag(wD, bD, stable, out.assignment, out.node_requested,
                 out.pv_claimed)
        last = (out, out_pre)
    np.asarray(last[0].assignment)
    if last[1] is not None:
        np.asarray(last[1].nominated)
    pipelined = (time.perf_counter() - t0) / snapshots

    # device-only time: dispatch the same DEVICE-RESIDENT buffers
    # repeatedly, force once (numpy args would add an upload per rep);
    # stable state recomputed for the CURRENT spec — the throughput loop
    # may have switched regimes, and a stale dict would shape-mismatch.
    # Carry mode: the carry is current for these buffers; the decision
    # chain is carry-update(empty) elided + cycle + preemption, and the
    # diagnosis program is timed separately (diag_ms — off the decision
    # path in serving).
    wbuf = jax.device_put(wbuf)
    bbuf = jax.device_put(bbuf)
    cycle_c, preempt, stable_fn, keeper, diag = fns
    stable = stable_state(spec, stable_fn, wbuf, bbuf)
    reps = 6
    carry_now = keeper.carry if keeper is not None else None

    def time_device_block():
        t0 = time.perf_counter()
        for _ in range(reps):
            out = (
                cycle_c(wbuf, bbuf, stable, carry_now)
                if use_carry else cycle_c(wbuf, bbuf, stable)
            )
            if preempt is not None:
                out_pre = preempt(wbuf, bbuf, out, stable)
        np.asarray(out.assignment)
        if preempt is not None:
            np.asarray(out_pre.nominated)
        return max((time.perf_counter() - t0 - tunnel_rt) / reps, 0.0), out

    # two blocks, take the min: a one-off executable-cache retry (see
    # core.cycle._Resilient) re-traces inside the timed window and would
    # otherwise report seconds of compile as device time
    d1, out = time_device_block()
    d2, out = time_device_block()
    device_s = min(d1, d2)

    diag_ms = 0.0
    if diag is not None:
        def time_diag_block():
            t0 = time.perf_counter()
            for _ in range(reps):
                d = diag(wbuf, bbuf, stable, out.assignment,
                         out.node_requested, out.pv_claimed)
            np.asarray(d)
            return max((time.perf_counter() - t0 - tunnel_rt) / reps, 0.0)

        d = diag(wbuf, bbuf, stable, out.assignment, out.node_requested,
                 out.pv_claimed)
        np.asarray(d)
        diag_ms = min(time_diag_block(), time_diag_block()) * 1e3

    journal_stats = None
    if journal is not None:
        # untimed: drain + fsync the tail, report writer-side stats
        journal.flush()
        journal_stats = journal.status()
        journal.close()

    p50 = _percentile(times, 50)
    p99 = _percentile(times, 99)
    # split-phase overlap accounting: how much of the host encode hides
    # behind device execution in the pipelined (production-driver) loop.
    # The serial baseline must be composed of the SAME per-cycle work the
    # pipelined loop dispatches: cycle + preemption (both inside
    # device_s's rep block) PLUS the per-snapshot diagnosis dispatch
    # (timed separately as diag_ms) — mismatched baselines would let the
    # estimate peg at 0%/100% regardless of actual overlap.
    from k8s_scheduler_tpu.core.profiling import overlap_stats

    ov = overlap_stats(
        _percentile(encode_times, 50), device_s + diag_ms / 1e3, pipelined
    )
    # tunnel-stall transparency: the rig's dispatch round-trip
    # occasionally stalls for tens of seconds (observed: one 28 s cycle
    # in an otherwise ~0.5 s p50 run, absent on rerun); cycles beyond
    # 10x p50 are counted so a stall-inflated p99 is identifiable
    # without excluding anything from the reported percentiles
    stall_cycles = sum(1 for t in times if p50 > 0 and t > 10 * p50)
    # ...and the same latency series through the PRODUCTION anomaly
    # classifier (core/observe.py): each forced-sync cycle ends in the
    # blocking tunnel read, so the runtime sentinel's stall rule applies
    # verbatim. `anomalies: {class: count}` makes the 28 s-outlier class
    # diffable across BENCH_rN artifacts (scripts/bench_diff.py).
    from k8s_scheduler_tpu.core.observe import classify_latency_series

    anomalies = classify_latency_series(times)
    # ...and once more through the WATCHTOWER rule pack (metrics/rules):
    # the same series replayed against the built-in alert rules with a
    # 1 s-per-cycle virtual clock, so a headline run that would have
    # paged in production says so in the artifact (`alerts_fired`, a
    # bench_diff count metric like stall_cycles)
    from k8s_scheduler_tpu.metrics.rules import replay_alerts

    alert_replay = replay_alerts(times)
    # multi-cycle K-sweep (BENCH_MULTI_K="1,4,8,16" or "1" to disable):
    # effective per-cycle RT of a K-cycle device batch vs the single
    # dispatch, surfaced as tunnel_amortization / effective_cycle_p50_ms
    # so scripts/bench_diff.py can tripwire them directionally
    multi: dict | None = None
    if multi_ks:
        multi = run_multicycle_config(cfg, k_values=tuple(multi_ks))
    return {
        **(
            {
                "multi_cycle": multi,
                **{
                    k: multi[k]
                    for k in (
                        "tunnel_amortization", "effective_cycle_p50_ms",
                        "first_bind_p50_ms", "speculation_hit_rate",
                    )
                    if k in multi
                },
            }
            if multi is not None else {}
        ),
        "config": cfg,
        "commit_mode": mode,
        "name": CONFIG_NAMES[cfg],
        "pods": P_real,
        "nodes": N_real,
        "snapshots": snapshots,
        "churn": churn,
        "decisions_per_sec": round(P_real * N_real / max(pipelined, 1e-9), 1),
        "pipelined_ms": round(pipelined * 1e3, 3),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
        "stall_cycles": stall_cycles,
        "anomalies": anomalies,
        "alerts_fired": alert_replay["alerts_fired"],
        **(
            {"alert_rules_fired": alert_replay["fired_rules"]}
            if alert_replay["fired_rules"] else {}
        ),
        "device_ms": round(device_s * 1e3, 3),
        "diag_ms": round(diag_ms, 3),
        "fetch_bytes": fetch_bytes,
        "overlap_pct": ov["overlap_pct"],
        "encode_hidden_ms": ov["encode_hidden_ms"],
        "tunnel_rt_ms": round(tunnel_rt * 1e3, 3),
        "tunnel_rt_p99_ms": round(tunnel_rt_p99 * 1e3, 3),
        "encode_p50_ms": round(_percentile(encode_times, 50) * 1e3, 3),
        "compile_seconds": round(compile_s, 2),
        "distinct_shapes": len(shape_keys),
        "fold_binds": fold_binds,
        "fold_skipped": fold_skipped,
        "fold_hits": getattr(enc, "fold_hits", 0),
        "delta_hits": enc.delta_hits,
        "full_encodes": enc.full_encodes,
        **(
            {"journal_appends": journal_appends,
             "journal": journal_stats}
            if journal_stats is not None else {}
        ),
        **{k: v // max(snapshots, 1) for k, v in totals.items()},
    }


def _mc_speculative_point(
    cfg: int, k: int, batches: int, group_pods: int
) -> "dict | None":
    """Scheduler-driven depth-2 measurement for one K-sweep point
    (ISSUE 13): a REAL multiCycleK=K + speculativeDispatch scheduler
    serves `batches` flushes of K arrival groups, and the point
    reports what the raw-program sweep cannot see —

    - `first_bind_p50_ms`: the streamed-fetch window from batch flush
      to the first inner cycle's decisions landing (flight-record
      `first_bind` phase) — ~1 inner cycle under depth-2 instead of
      the whole K-cycle batch;
    - `sched_batch_p50_ms` / `sched_effective_p50_ms`: wall p50 of the
      flush cycle (encode + depth-2 dispatches + streamed apply) and
      its per-inner-cycle share;
    - `speculation_hit_rate`: adopted / (adopted + abandoned) from the
      scheduler_speculation_total ledger (a clean drive adopts every
      batch: 1.0).

    Returns None when the drive never speculated (nothing to report).
    """
    import time as _t

    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core import Scheduler

    base_nodes, _base_existing = make_config_base(cfg)
    clk = [0.0]  # manual clock: assumed-pod TTLs must not fire mid-run
    cfg_obj = SchedulerConfiguration(
        multi_cycle_k=k,
        multi_cycle_max_wait_ms=1e12,
        speculative_dispatch=True,
        # sticky pre-sizing: binds fold into the existing set every
        # flush, and an E/MPN regime flip mid-sweep would measure
        # compiles, not dispatch
        pad_existing=_pad(group_pods * k * batches + 64),
        pad_pods_per_node=256,
        speculative_compile=False,
    )
    sched = Scheduler(
        config=cfg_obj, binder=lambda p, n: None, now=lambda: clk[0],
    )
    for nd in base_nodes:
        sched.on_node_add(nd)
    flush_walls = []
    for bi in range(batches):
        for gi in range(k):
            pods, _g = make_config_pending(
                cfg, seed=bi * k + gi, count=group_pods,
                name_prefix=f"sp{bi}-{gi}-",
            )
            for p in pods:
                sched.on_pod_add(p)
            t0 = _t.perf_counter()
            sched.schedule_cycle()
            if gi == k - 1:  # the buffer reached K: this cycle flushed
                flush_walls.append(_t.perf_counter() - t0)
    led = sched.speculation_ledger()
    attempts = led["adopted"] + led["abandoned"]
    if attempts == 0:
        return None
    first_binds = [
        r.phases["first_bind_ms"]
        for r in sched.flight.snapshot()
        if "first_bind_ms" in r.phases
    ]
    batch_p50 = _percentile(flush_walls, 50)
    out = {
        "sched_batch_p50_ms": round(batch_p50 * 1e3, 3),
        "sched_effective_p50_ms": round(batch_p50 / k * 1e3, 3),
        "speculation_hit_rate": round(led["adopted"] / attempts, 4),
        "speculation_ledger": led,
    }
    if first_binds:
        out["first_bind_p50_ms"] = round(
            _percentile(first_binds, 50), 3
        )
    return out


def run_multicycle_config(
    cfg: int,
    k_values=(1, 4, 8, 16),
    batches: int = 6,
    group_pods: int = 64,
) -> dict:
    """The multi-cycle K-sweep axis (ROADMAP item 1): effective
    per-cycle round trip of a K-cycle device-resident batch
    (core/cycle.build_packed_multicycle_fn) over SMALL-DELTA arrival
    groups, against the K=1 single-dispatch baseline.

    Reports, per K: the forced-sync batch p50 (encode K groups + one
    dispatch + the one stacked slimmed fetch) and the EFFECTIVE
    per-cycle round trip `batch_p50 / K` — the number the amortization
    story is about (`tunnel_rt / K` instead of `tunnel_rt` per cycle).
    `tunnel_amortization` = K=1 effective p50 / best-K effective p50.

    Only configs whose workload sits inside the exactness envelope
    sweep (no inter-pod affinity/spread/volumes/ports — configs 3/4
    report `skipped` with the gating capability, exactly like the
    serving fallback); config 5's gang draw has no small-group shape.
    """
    import jax
    import numpy as np

    from k8s_scheduler_tpu.core.cycle import (
        build_packed_multicycle_fn,
        multicycle_unsupported_reason,
    )
    from k8s_scheduler_tpu.core.pipeline import build_multicycle_slim_fn
    from k8s_scheduler_tpu.models import SnapshotEncoder, packing

    if cfg == 5:
        return {"skipped": "gang_group_draw"}
    _P_real, N_real = CONFIG_SHAPES[cfg]
    base_nodes, base_existing = make_config_base(cfg)
    enc = SnapshotEncoder(
        pad_pods=_pad(group_pods, 64), pad_nodes=_pad(N_real)
    )

    def draw_group(seed: int):
        pods, _g = make_config_pending(
            cfg, seed=seed, count=group_pods, name_prefix=f"mc{seed}-"
        )
        return enc.encode(base_nodes, pods, base_existing)

    snap0 = draw_group(0)
    reason = multicycle_unsupported_reason(snap0)
    if reason is not None:
        return {"skipped": reason}
    spec = packing.make_spec(snap0)
    max_k = max(k_values)
    # one spec for the whole sweep: pre-encode max_k x batches groups,
    # verify the regime never flips (grow-only dictionaries settle
    # after the first draws), pack once
    packed = [packing.pack(snap0, spec)]
    for s in range(1, max_k * batches):
        snap = draw_group(s)
        sp = packing.make_spec(snap)
        if sp.key() != spec.key():
            # re-encode the settled regime from the top
            spec = sp
            packed = [
                packing.pack(draw_group(j), spec)
                for j in range(s + 1)
            ]
        else:
            packed.append(packing.pack(snap, spec))
    slim = build_multicycle_slim_fn(N_real)
    per_k: dict[str, dict] = {}
    baseline_eff = None
    best_eff = None
    best_k = 1
    for k in sorted(k_values):
        mfn = build_packed_multicycle_fn(spec, k=k)
        # warmup/compile outside the timed window
        w0 = np.stack([packed[j % len(packed)][0] for j in range(k)])
        b0 = np.stack([packed[j % len(packed)][1] for j in range(k)])
        res = mfn(jax.device_put(w0), jax.device_put(b0), None,
                  np.int32(k))
        jax.device_get(
            slim(res.assignment, res.unschedulable, res.gang_dropped,
                 res.attempted, res.cycles_run)
        )
        times = []
        for bi in range(batches):
            rows = [
                packed[(bi * k + j) % len(packed)] for j in range(k)
            ]
            t0 = time.perf_counter()
            wb = jax.device_put(np.stack([w for w, _ in rows]))
            bb = jax.device_put(np.stack([b for _, b in rows]))
            res = mfn(wb, bb, None, np.int32(k))
            a, flags, ran = jax.device_get(
                slim(res.assignment, res.unschedulable,
                     res.gang_dropped, res.attempted, res.cycles_run)
            )
            times.append(time.perf_counter() - t0)
            assert int(ran) == k
        batch_p50 = _percentile(times, 50)
        eff = batch_p50 / k
        stall = sum(
            1 for t in times if batch_p50 > 0 and t > 10 * batch_p50
        )
        per_k[str(k)] = {
            "batch_p50_ms": round(batch_p50 * 1e3, 3),
            "effective_p50_ms": round(eff * 1e3, 3),
            "stall_cycles": stall,
        }
        if k == 1:
            baseline_eff = eff
        if best_eff is None or eff < best_eff:
            best_eff, best_k = eff, k
    out = {
        "group_pods": group_pods,
        "batches": batches,
        "per_k": per_k,
        "best_k": best_k,
    }
    if baseline_eff and best_eff:
        out["tunnel_amortization"] = round(baseline_eff / best_eff, 2)
        out["effective_cycle_p50_ms"] = round(best_eff * 1e3, 3)
    # depth-2 speculative serving (ISSUE 13): scheduler-driven
    # first-bind latency + speculation hit rate per K>=2 point, with
    # the headline taken at the best (lowest-first-bind) point —
    # scripts/bench_diff.py gates first_bind_p50_ms (rise = regressed)
    # and speculation_hit_rate (drop = regressed) directionally
    spec_first = None
    spec_rate = None
    for k in sorted(k_values):
        if k < 2:
            continue
        pt = _mc_speculative_point(cfg, k, batches, group_pods)
        if pt is None:
            continue
        per_k[str(k)].update(pt)
        fb = pt.get("first_bind_p50_ms")
        if fb is not None and (spec_first is None or fb < spec_first):
            spec_first = fb
        rate = pt["speculation_hit_rate"]
        if spec_rate is None or rate < spec_rate:
            spec_rate = rate  # conservative: the worst point gates
    if spec_first is not None:
        out["first_bind_p50_ms"] = spec_first
    if spec_rate is not None:
        out["speculation_hit_rate"] = spec_rate
    return out


def run_regime_churn_config(snapshots: int = 36) -> dict:
    """Config 6: the pad-bucket-crossing churn soak. A real Scheduler
    (flight recorder + observer + persistent compile cache) serves a
    pending stream oscillating across the P=64/128 pad-bucket boundary,
    three times over one shared cache directory:

    - **cold**: empty cache — every regime compiles; `compile_seconds`
      is the cold cost, `regime_flips` counts the boundary crossings,
      and `compile_stall_cycles` counts cycles that paid >50 ms of
      program (re)build AFTER the first traversal of each regime — the
      ISSUE 8 acceptance metric (the memo + cache must absorb every
      later flip, so this must be 0).
    - **hysteresis**: same trace with padHysteresisPct=20 — the larger
      regime holds, `hysteresis_flips` counts what remains (expect 1:
      the initial up-step).
    - **warm**: a fresh Scheduler (fresh jit wrappers — the in-process
      restart analogue) against the now-populated cache: zero cold
      compiles for previously-seen regimes; `warm_compile_seconds` is
      the total trace+load cost that replaced them and
      `compile_cache_hit_rate` feeds bench_diff's directional gate.

    The sticky E/MPN pads are pre-sized (the documented fold-mode
    deployment pattern) so the oscillation exercises exactly ONE
    dimension — P — and flips are deterministic."""
    import shutil
    import tempfile

    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core import Scheduler
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    hi, n_nodes = CONFIG_SHAPES[6]
    # _pad(60)=64 vs _pad(80)=128: one boundary crossed every cycle.
    # lo sits just under the boundary (60/64 = 6% headroom) so the
    # hysteresis phase's 20% down-step margin HOLDS the larger regime —
    # a lo leaving more headroom than the margin would legitimately
    # step down, which is the knob working, not a flip
    lo = 60
    cache_dir = os.environ.get("BENCH_COMPILE_CACHE_DIR", "")
    ephemeral = not cache_dir
    if ephemeral:
        cache_dir = tempfile.mkdtemp(prefix="bench_regime_churn_cc_")
    nodes = make_cluster(n_nodes)

    def drive(hysteresis_pct: float) -> dict:
        cfg_obj = SchedulerConfiguration(
            compile_cache_dir=cache_dir,
            pad_existing=4096,
            pad_pods_per_node=1024,
            pad_hysteresis_pct=hysteresis_pct,
            speculative_compile=False,  # the cache is the subject here;
            # speculation would race the oscillation nondeterministically
        )
        # manual clock: cold-phase compiles take real seconds, and the
        # assumed-pod TTL expiring mid-soak would requeue bound pods
        # into later cycles' pending sets — moving P off the scripted
        # oscillation (the multicycle PR hit the same seed behavior)
        clk = [0.0]
        sched = Scheduler(
            config=cfg_obj, binder=lambda p, n: None,
            now=lambda: clk[0],
        )
        for nd in nodes:
            sched.on_node_add(nd)
        seq = 0
        t0 = time.perf_counter()
        for i in range(snapshots):
            count = hi if i % 2 else lo
            for p in make_pods(
                count, seed=9000 + i, name_prefix=f"rc{seq}-"
            ):
                sched.on_pod_add(p)
                seq += 1
            sched.schedule_cycle()
            clk[0] += 0.05
        wall = time.perf_counter() - t0
        recs = sched.flight.snapshot()
        # builds = regime_flip stamps (memo misses that paid a program
        # build); sig flips = what the WORKLOAD did (consecutive-cycle
        # signature changes) — hysteresis shrinks the latter, the memo
        # + persistent cache absorb the former
        flips = [r for r in recs if r.counts.get("regime_flip")]
        sig_flips = sum(
            1 for a, b in zip(recs, recs[1:]) if a.sig != b.sig
        )
        compile_s = sum(
            r.phases.get("compile_ms", 0.0) for r in recs
        ) / 1e3
        # the first traversal = the first build of each DISTINCT regime
        # (two here); every compile-attributed cycle after it is a
        # stall the cache/memo should have absorbed
        seen: set = set()
        stall_after_first = 0
        for r in recs:
            key = r.sig
            fresh_regime = key not in seen
            seen.add(key)
            if r.phases.get("compile_ms", 0.0) > 50.0 and not fresh_regime:
                stall_after_first += 1
        cc = sched._compile_cache
        return {
            "wall_s": round(wall, 2),
            "cycles": len(recs),
            "regime_flips": sig_flips,
            "regime_builds": len(flips),
            "compile_seconds": round(compile_s, 2),
            "compile_stall_cycles": stall_after_first,
            "sources": sorted(
                {r.compile_source for r in flips if r.compile_source}
            ),
            "cache": cc.status() if cc is not None else {},
        }

    from k8s_scheduler_tpu.core import compile_cache as _cc

    try:
        cold = drive(0.0)
        hyst = drive(20.0)
        # the warm phase must measure real executable DESERIALIZATION
        # (the restart path the cache exists to prove), not the
        # process-level loaded-executable memo the earlier drives
        # populated — clear it, as a fresh process would start
        _cc.clear_loaded_memo()
        warm = drive(0.0)
    finally:
        if ephemeral:
            shutil.rmtree(cache_dir, ignore_errors=True)
    attempts = warm["cache"]["hits"] + warm["cache"]["misses"]
    hit_rate = warm["cache"]["hits"] / attempts if attempts else 0.0
    return {
        "config": 6,
        "name": CONFIG_NAMES[6],
        "pods": hi,
        "nodes": n_nodes,
        "snapshots": snapshots,
        "regime_flips": cold["regime_flips"],
        "hysteresis_flips": hyst["regime_flips"],
        "compile_seconds": cold["compile_seconds"],
        "warm_compile_seconds": warm["compile_seconds"],
        "warm_load_p50_ms": round(
            warm["cache"].get("load_p50_s", 0.0) * 1e3, 1
        ),
        "cache_hits": warm["cache"]["hits"],
        "cache_misses": warm["cache"]["misses"],
        "compile_cache_hit_rate": round(hit_rate, 3),
        # acceptance metrics: zero compile-attributed stall cycles
        # after the first traversal of each regime, in every phase
        "stall_cycles": (
            cold["compile_stall_cycles"]
            + hyst["compile_stall_cycles"]
            + warm["compile_stall_cycles"]
        ),
        "warm_sources": warm["sources"],
        "detail": {"cold": cold, "hysteresis": hyst, "warm": warm},
    }


def chaos_serve_drive(
    fault_spec: str,
    cycles: int,
    deadline_ms: float,
    pods_per_cycle: int = 4,
    n_nodes: int = 16,
    cache_dir: str = "off",
    promote_cycles: int = 4,
    drain_timeout_s: float = 30.0,
) -> dict:
    """The shared chaos-serve harness (ISSUE 9): one real Scheduler
    (dispatch watchdog + ladder + pre-sized pads so no regime flip
    pollutes the timing) serves a steady arrival stream under
    `fault_spec`, then drains until every added pod bound and the
    ladder promoted home (or `drain_timeout_s` expires). Used by bench
    config 7 (`run_fault_storm_config`) and scripts/soak_chaos.py's
    serve phase, so the two can never assert different invariants of
    the same storm.

    Returns raw facts — `sched` (live handle), `added`, `binds`
    (uid -> bind count), per-cycle `walls`, `degraded_cycles` (flight
    records with rung > 0), `episodes_ms` (completed recovery episodes),
    `duplicate_binds`, `lost` — and leaves the fault plan ARMED so the
    caller can probe `faults.plan()`; the caller must
    `faults.disarm()` when done."""
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    cfg_obj = SchedulerConfiguration(
        dispatch_deadline_ms=deadline_ms,
        degrade_promote_cycles=promote_cycles,
        fault_spec=fault_spec,
        # backoff short so DispatchFailed pods retry within the drive
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.2,
        # pre-sized pads: the oscillation-free workload must not flip
        # regimes, so the deadline assertions are compile-free
        pad_existing=2048,
        pad_pods_per_node=512,
        compile_cache_dir=cache_dir,
        speculative_compile=False,
    )
    binds: dict[str, int] = {}
    added: set[str] = set()
    sched = Scheduler(
        config=cfg_obj,
        binder=lambda p, n: binds.__setitem__(
            p.uid, binds.get(p.uid, 0) + 1
        ),
    )
    for nd in make_cluster(n_nodes):
        sched.on_node_add(nd)
    walls: dict[int, float] = {}
    t_run = time.perf_counter()
    for i in range(1, cycles + 1):
        for p in make_pods(
            pods_per_cycle, seed=5000 + i, name_prefix=f"cz{i}-"
        ):
            sched.on_pod_add(p)
            added.add(p.uid)
        t0 = time.perf_counter()
        sched.schedule_cycle()
        walls[i] = time.perf_counter() - t0
    # drain tail: requeued pods bind, ladder promotes home
    drain_deadline = time.perf_counter() + drain_timeout_s
    while (
        len(binds) < len(added) or sched.ladder.rung > 0
    ) and time.perf_counter() < drain_deadline:
        sched.schedule_cycle()
        time.sleep(0.02)
    recs = sched.flight.snapshot(last=4096)
    return {
        "sched": sched,
        "added": added,
        "binds": binds,
        "walls": walls,
        "wall_s": time.perf_counter() - t_run,
        "degraded_cycles": sum(
            1 for r in recs if r.counts.get("rung", 0) > 0
        ),
        "episodes_ms": sched.ladder.recovery_episodes_ms(),
        "duplicate_binds": sum(1 for n in binds.values() if n > 1),
        "lost": sorted(
            added - set(binds)
            - {p.uid for p in sched.queue.all_pending()}
        ),
    }


def run_fault_storm_config(snapshots: int = 40) -> dict:
    """Config 7: the fault-storm soak (ISSUE 9), on the shared
    `chaos_serve_drive` harness. The plan fires a `fetch_hang` LONGER
    than the 300 ms dispatch deadline (the watchdog must bound the
    serve loop — `max_blocked_ms` reports the hang cycle's wall) and
    one `device_error` per marker class: transport and corrupt are
    absorbed in-cycle by `_Resilient` (no rung change), wedge fails
    fast and steps the ladder.

    Headline metrics, both gated directionally by bench_diff:

    - `mttr_ms` — mean wall ms from leaving rung 0 to returning
      (ladder transition timestamps; rise = regressed recovery);
    - `degraded_cycles` — cycles spent below rung 0 (rise = regressed).

    The run FAILS (raises) if a pod is lost, binds twice, the hang
    cycle blocks past half the injected hang, or the ladder never
    recovers — the bench is the acceptance test run at fleet cadence."""
    from k8s_scheduler_tpu.core import faults

    n_nodes = CONFIG_SHAPES[7][1]
    deadline_ms, hang_ms = 300.0, 2500.0
    cycles = max(snapshots, 28)  # the plan's last fault fires at 20
    try:
        d = chaos_serve_drive(
            fault_spec=(
                "seed=11;"
                f"fetch_hang@cycle=8:ms={hang_ms}:n=1;"
                "device_error@cycle=12:kind=transport:n=1;"
                "device_error@cycle=16:kind=corrupt:n=1;"
                "device_error@cycle=20:kind=wedge:n=1"
            ),
            cycles=cycles,
            deadline_ms=deadline_ms,
            n_nodes=n_nodes,
        )
        sched = d["sched"]
        episodes = d["episodes_ms"]
        max_blocked_ms = d["walls"][8] * 1e3
        if d["lost"] or d["duplicate_binds"]:
            raise AssertionError(
                f"fault_storm invariants violated: lost={d['lost']} "
                f"duplicate_binds={d['duplicate_binds']}"
            )
        if max_blocked_ms > hang_ms * 0.5:
            raise AssertionError(
                f"serve loop blocked {max_blocked_ms:.0f} ms against a "
                f"{deadline_ms:.0f} ms deadline — watchdog failed"
            )
        if sched.ladder.rung != 0 or not episodes:
            raise AssertionError(
                "ladder never degraded-and-recovered "
                f"(rung={sched.ladder.rung}, episodes={episodes})"
            )
        return {
            "config": 7,
            "name": CONFIG_NAMES[7],
            "pods": len(d["added"]),
            "nodes": n_nodes,
            "snapshots": cycles,
            "wall_s": round(d["wall_s"], 2),
            "scheduled": len(d["binds"]),
            "mttr_ms": round(sum(episodes) / len(episodes), 1),
            "mttr_max_ms": round(max(episodes), 1),
            "degraded_cycles": d["degraded_cycles"],
            "degradations": sched.ladder.degradations,
            "deadline_ms": deadline_ms,
            "max_blocked_ms": round(max_blocked_ms, 1),
            "fired_points": sorted(
                faults.plan().fired_points()
                if faults.plan() is not None else []
            ),
            "transitions": [
                (t["from_name"], t["to_name"])
                for t in sched.ladder.transitions
            ],
        }
    finally:
        faults.disarm()


def front_door_drive(
    duration_s: float,
    rate_pps: float,
    queue_depth: int = 0,
    n_nodes: int = 16,
    batch: int = 4,
    state_dir: str = "",
    fault_spec: str = "",
    deadline_ms: float = 0.0,
    multi_cycle_k: int = 4,
    drain_timeout_s: float = 60.0,
    promote_cycles: int = 4,
    name_prefix: str = "ld",
    release_after_bind: bool = True,
    incremental: bool = False,
    trace_rate: float = 0.0,
    on_tick=None,
) -> dict:
    """The shared open-loop front-door harness (ISSUE 14): one real
    Scheduler behind an AdmissionController + FrontDoor serve loop; the
    caller's thread plays the open-loop client — submissions fire at
    wall-clock arrival times derived from `rate_pps` REGARDLESS of how
    fast binds complete (arrival-rate-driven, never closed-loop), so
    overload actually overloads instead of self-throttling. Used by
    bench config 9 (`run_front_door_config`), scripts/loadgen.py's
    in-process mode, and scripts/soak_chaos.py's overload phase, so the
    bench, the load tool, and the soak can never assert different
    invariants of the same front door.

    Returns raw facts: `sched`/`admission` (live handles), `acked`
    (uid -> submit wall time), `binds` (uid -> (count, bind wall
    time)), `ack_lat_s`, `shed`/`accepted` counts, `max_depth` (the
    deepest queue_depth any ack/shed reported), `duplicate_binds`,
    `lost` (acked pods that neither bound nor remain tracked),
    `drained`. Leaves any fault plan ARMED (caller disarms), exactly
    like chaos_serve_drive.

    `trace_rate` > 0 arms pod-lifecycle tracing (core/spans.py) at
    that head-sampling rate for the duration of the drive and disarms
    it on the way out — config 9's trace-overhead stage runs the
    sustained drive at rate 1.0 against the rate-0 baseline."""
    from k8s_scheduler_tpu.config import SchedulerConfiguration
    from k8s_scheduler_tpu.core.scheduler import Scheduler
    from k8s_scheduler_tpu.service.admission import (
        AdmissionController,
        FrontDoor,
    )
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    state = None
    if state_dir:
        from k8s_scheduler_tpu.state import DurableState

        state = DurableState(state_dir, snapshot_interval_seconds=0)
    cfg_obj = SchedulerConfiguration(
        admission_queue_depth=queue_depth,
        multi_cycle_k=multi_cycle_k,
        multi_cycle_max_wait_ms=5.0,
        dispatch_deadline_ms=deadline_ms,
        degrade_promote_cycles=promote_cycles,
        fault_spec=fault_spec,
        pod_initial_backoff_seconds=0.05,
        pod_max_backoff_seconds=0.2,
        # pre-sized pads: regime flips mid-drive would bill compile
        # time to submit->bind latency
        pad_existing=2048,
        pad_pods_per_node=512,
        compile_cache_dir="off",
        speculative_compile=False,
        incremental_encode=incremental,
    )
    binds: dict[str, tuple[int, float]] = {}
    confirm_q: "collections.deque" = collections.deque()

    def binder(p, n):
        c, t = binds.get(p.uid, (0, 0.0))
        binds[p.uid] = (c + 1, time.perf_counter())
        confirm_q.append((p, n))

    _spans = None
    if trace_rate > 0:
        from k8s_scheduler_tpu.core import spans as _spans

        _spans.arm(rate=trace_rate)
    sched = Scheduler(config=cfg_obj, binder=binder, state=state)
    admission = AdmissionController(sched)
    for nd in make_cluster(n_nodes):
        admission.node_churn(adds=[nd])

    def confirm_binds():
        # informer playback on the loop thread (a real deployment's
        # agent confirms via Update): without it an assumed pod
        # expires on the TTL and re-binds, which the duplicate-bind
        # invariant would — correctly — flag. With
        # `release_after_bind` the confirmed pod is then deleted (a
        # fast-jobs workload): node capacity recycles, so the drive
        # measures SERVING throughput instead of filling n_nodes and
        # stalling on cluster capacity
        while confirm_q:
            p, n = confirm_q.popleft()
            sched.on_pod_add(p, n)
            if release_after_bind:
                sched.on_pod_delete(p.uid)

    fd = FrontDoor(admission, post_cycle=confirm_binds)
    fd.start()
    acked: dict[str, float] = {}
    ack_lat: list[float] = []
    shed = 0
    max_depth = 0
    seq = 0
    t_start = time.perf_counter()
    t0 = t_start  # reassigned when the open-loop window opens
    try:
        # warmup OUTSIDE the timed window: the first dispatch compiles
        warm = make_pods(batch, seed=999, name_prefix=f"{name_prefix}w-")
        r = admission.submit(warm)
        assert r.ok, f"warmup submission rejected: {r.reason}"
        # warmup pods are NOT recorded in `acked`: their bind time
        # embeds the first-dispatch compile, and joining them into the
        # submit->bind latencies would make the gated p99 report
        # compile noise instead of the steady-state SLO (they are
        # asserted fully bound right here, so the lost/dup accounting
        # does not need them)
        while len(binds) < len(warm):
            if time.perf_counter() - t_start > 120:
                raise AssertionError("warmup never bound (compile hang?)")
            time.sleep(0.01)

        # the open-loop window: arrival i is DUE at t0 + i/rate; send
        # every batch that is due, sleep only until the next arrival
        t0 = time.perf_counter()
        interval = batch / rate_pps
        n_batches = max(int(duration_s / interval), 1)
        for i in range(n_batches):
            due = t0 + i * interval
            now = time.perf_counter()
            if now < due:
                time.sleep(due - now)
            seq += 1
            pods = make_pods(
                batch, seed=10_000 + seq,
                name_prefix=f"{name_prefix}{seq}-",
            )
            t_sub = time.perf_counter()
            res = admission.submit(pods)
            if res.queue_depth > max_depth:
                max_depth = res.queue_depth
            if res.ok:
                ack_lat.append(time.perf_counter() - t_sub)
                for p in pods:
                    acked[p.uid] = t_sub
            else:
                shed += res.shed
            if on_tick is not None:
                # mid-burst probe hook: soak_chaos's overload phase
                # evaluates the real /healthz closure in here
                on_tick(sched, admission, res)
        # drain: every acked pod resolves (bound, or parked in a tier),
        # and — when a fault plan degraded the ladder — rung 0 returns.
        # While the ladder sits below rung 0 a probe trickle keeps
        # flowing (promotion counts clean DISPATCHING cycles: a silent
        # queue earns no recovery evidence; this is the recovery-tail
        # role the fuzz chaos traces generate explicitly)
        deadline = time.perf_counter() + drain_timeout_s
        while (
            (any(u not in binds for u in acked) or sched.ladder.rung > 0)
            and time.perf_counter() < deadline
        ):
            if sched.ladder.rung > 0:
                seq += 1
                probe = make_pods(
                    1, seed=90_000 + seq,
                    name_prefix=f"{name_prefix}rt{seq}-",
                )
                r = admission.submit(probe)
                if r.ok:
                    acked[probe[0].uid] = time.perf_counter()
            time.sleep(0.05)
    finally:
        drained = fd.stop()
        if _spans is not None:
            _spans.disarm()
    tracked = {p.uid for p in sched.queue.all_pending()}
    bind_ts = [t for _c, t in binds.values() if t >= t0]
    return {
        "sched": sched,
        "admission": admission,
        "state": state,
        "acked": acked,
        "binds": binds,
        "ack_lat_s": ack_lat,
        "accepted": len(acked),
        "shed": shed,
        "max_depth": max_depth,
        "wall_s": time.perf_counter() - t_start,
        # serving rate over the open-loop window (warmup excluded):
        # binds landed after t0, divided by the window they landed in —
        # the capacity estimate config 9's calibration stage reads
        "bind_rate_pps": (
            len(bind_ts) / max(max(bind_ts) - t0, 1e-6)
            if bind_ts else 0.0
        ),
        "duplicate_binds": sum(
            1 for c, _t in binds.values() if c > 1
        ),
        "lost": sorted(set(acked) - set(binds) - tracked),
        "drained": drained,
        "cycles": fd.cycles,
    }


# the submit->ack path embeds the shared group-commit fsync, and on a
# real disk that barrier is BIMODAL across whole drive stages (~0.3 ms
# vs ~4 ms p99 run to run, journal/flusher state — measured on the
# same tree both ways): an ack-p99 ratio between two stages can read
# +1000% with zero code difference. Ack deltas under this floor are
# fsync jitter, not tracing cost; deltas past it are the catastrophic
# regressions the ceiling gate exists for.
_TRACE_ACK_FLOOR_MS = 10.0


def trace_overhead_pct(
    base_ack99_ms: float,
    traced_ack99_ms: float,
    base_bind50_ms: float,
    traced_bind50_ms: float,
) -> float:
    """Worst-case armed-tracing overhead, robust to fsync bimodality.

    The larger of two deltas, floored at 0:

    - submit->ack p99, counting only the delta BEYOND
      `_TRACE_ACK_FLOOR_MS` and relative to a base no smaller than the
      floor (so a lucky-mode base can't inflate the ratio);
    - submit->bind p50, a plain relative delta — queue-dominated and
      stable, the canary for a serve-loop-serializing tracing bug.
    """
    ack_delta = traced_ack99_ms - base_ack99_ms - _TRACE_ACK_FLOOR_MS
    return max(
        ack_delta / max(base_ack99_ms, _TRACE_ACK_FLOOR_MS) * 100.0,
        (traced_bind50_ms - base_bind50_ms)
        / max(base_bind50_ms, 1e-9) * 100.0,
        0.0,
    )


def run_front_door_config(snapshots: int = 12) -> dict:
    """Config 9: the submission front door under open-loop load.

    Three stages on the shared `front_door_drive` harness:

    1. **calibrate** — a short burst measures serving capacity
       (binds/s) so the rates below scale to the machine instead of
       hardcoding a TPU-or-laptop-specific number;
    2. **sustained** — `snapshots/2` seconds at ~50% capacity: zero
       shed, zero lost, zero duplicates, and the headline latencies
       `submit_ack_p99_ms` (accept -> ack, including the
       WAL-before-ack fsync barrier) and `submit_bind_p50/p99_ms`
       (accept -> bind, end to end);
    3. **trace overhead** — the sustained drive again with
       pod-lifecycle tracing armed at sample rate 1.0 (every pod
       traced, the worst case): `trace_overhead_pct` (see the
       module-level function) is the larger of the submit->ack p99
       delta beyond the fsync-jitter floor and the plain submit->bind
       p50 delta vs stage 2, floored at 0 —
       `scripts/bench_diff.py --max-trace-overhead` gates it;
    4. **overload** — `snapshots/2` seconds at ~3x capacity against a
       small admission bound: the door MUST shed (RESOURCE_EXHAUSTED),
       queue depth must stay within the bound, and every pod that was
       ACKED must still bind exactly once — shed-not-lost.

    The run FAILS (raises) on any invariant violation — the bench is
    the acceptance test run at fleet cadence. `shed_rate` reported for
    bench_diff is the SUSTAINED phase's (0 unless admission started
    refusing nominal load — exactly the regression the gate exists
    for); the overload phase's shed rate rides `overload_shed_rate`."""
    n_nodes = CONFIG_SHAPES[9][1]
    env_rate = float(os.environ.get("BENCH_FRONT_DOOR_RATE", "0"))

    # one admission bound for calibration AND overload, sitting AT the
    # pod pad bucket (64): the whole bench serves one packed regime, so
    # no phase bills a mid-drive recompile to submit->bind latency
    depth_bound = 64

    # stage 1: calibrate capacity with an over-rate burst against the
    # bound (its sheds are calibration noise, not an invariant)
    cal = front_door_drive(
        duration_s=1.5, rate_pps=400.0, n_nodes=n_nodes,
        batch=4, queue_depth=depth_bound, name_prefix="cal",
    )
    cap_pps = max(cal["bind_rate_pps"], 20.0)
    if cal["lost"] or cal["duplicate_binds"]:
        raise AssertionError(
            f"front_door calibration violated invariants: "
            f"lost={cal['lost']} dup={cal['duplicate_binds']}"
        )

    # stage 2: sustained at ~half measured capacity
    sustained_rate = env_rate or max(cap_pps * 0.5, 10.0)
    d = front_door_drive(
        duration_s=max(snapshots / 2.0, 3.0),
        rate_pps=sustained_rate,
        n_nodes=n_nodes,
        batch=4,
        name_prefix="su",
    )
    if d["shed"] or d["lost"] or d["duplicate_binds"]:
        raise AssertionError(
            f"front_door sustained phase violated invariants: "
            f"shed={d['shed']} lost={d['lost']} "
            f"dup={d['duplicate_binds']}"
        )
    bind_lat_ms = sorted(
        (t_bind - d["acked"][u]) * 1e3
        for u, (_c, t_bind) in d["binds"].items()
        if u in d["acked"]
    )
    ack_ms = sorted(v * 1e3 for v in d["ack_lat_s"])

    # stage 3: the same sustained drive with tracing armed at rate 1.0
    # (every pod traced — the worst case, not the 1/64 default)
    tr = front_door_drive(
        duration_s=max(snapshots / 2.0, 3.0),
        rate_pps=sustained_rate,
        n_nodes=n_nodes,
        batch=4,
        name_prefix="tr",
        trace_rate=1.0,
    )
    if tr["shed"] or tr["lost"] or tr["duplicate_binds"]:
        raise AssertionError(
            f"front_door traced phase violated invariants: "
            f"shed={tr['shed']} lost={tr['lost']} "
            f"dup={tr['duplicate_binds']}"
        )
    tr_bind_ms = sorted(
        (t_bind - tr["acked"][u]) * 1e3
        for u, (_c, t_bind) in tr["binds"].items()
        if u in tr["acked"]
    )
    tr_ack_ms = sorted(v * 1e3 for v in tr["ack_lat_s"])
    trace_overhead = trace_overhead_pct(
        _percentile(ack_ms, 99),
        _percentile(tr_ack_ms, 99),
        _percentile(bind_lat_ms, 50),
        _percentile(tr_bind_ms, 50),
    )

    # stage 4: overload at ~3x capacity against the same small bound —
    # backlog grows at ~2x capacity, crosses the bound within a couple
    # of cycles, and the door must start refusing
    o = front_door_drive(
        duration_s=max(snapshots / 2.0, 4.0),
        rate_pps=max(cap_pps * 3.0, 60.0),
        queue_depth=depth_bound,
        n_nodes=n_nodes,
        batch=8,
        name_prefix="ov",
    )
    if not o["shed"]:
        raise AssertionError(
            "overload phase never shed: the admission bound is not "
            f"engaging (accepted={o['accepted']}, "
            f"rate {cap_pps * 3.0:.0f} pps vs capacity "
            f"{cap_pps:.0f} pps)"
        )
    if o["max_depth"] > depth_bound + 8:
        raise AssertionError(
            f"queue depth {o['max_depth']} exceeded the admission "
            f"bound {depth_bound}: backpressure is not bounding memory"
        )
    if o["lost"] or o["duplicate_binds"]:
        raise AssertionError(
            f"overload phase violated shed-not-lost: lost={o['lost']} "
            f"dup={o['duplicate_binds']}"
        )
    total_o = o["accepted"] + o["shed"]
    return {
        "config": 9,
        "name": CONFIG_NAMES[9],
        "pods": d["accepted"] + tr["accepted"] + total_o,
        "nodes": n_nodes,
        "snapshots": snapshots,
        "wall_s": round(
            d["wall_s"] + tr["wall_s"] + o["wall_s"] + cal["wall_s"], 2
        ),
        "scheduled": len(d["binds"]) + len(tr["binds"]) + len(o["binds"]),
        "capacity_pps": round(cap_pps, 1),
        "sustained_rate_pps": round(sustained_rate, 1),
        "submit_ack_p99_ms": round(_percentile(ack_ms, 99), 3),
        "submit_bind_p50_ms": round(_percentile(bind_lat_ms, 50), 3),
        "submit_bind_p99_ms": round(_percentile(bind_lat_ms, 99), 3),
        "trace_overhead_pct": round(trace_overhead, 2),
        "traced_submit_ack_p99_ms": round(_percentile(tr_ack_ms, 99), 3),
        "traced_submit_bind_p50_ms": round(
            _percentile(tr_bind_ms, 50), 3
        ),
        "shed_rate": 0.0,  # sustained-phase shed (asserted zero above)
        "accepted": d["accepted"],
        "shed": d["shed"],
        "overload_shed_rate": round(o["shed"] / max(total_o, 1), 4),
        "overload_accepted": o["accepted"],
        "overload_shed": o["shed"],
        "max_queue_depth": o["max_depth"],
        "queue_depth_bound": depth_bound,
        "drained": bool(d["drained"] and o["drained"]),
    }


def run_host_encode_config(snapshots: int = 12) -> dict:
    """Config 10: admission-time incremental encode through the REAL
    Submit path (ISSUE 16). Four stages on the shared
    `front_door_drive` harness:

    1. **calibrate** — a short burst measures serving capacity so the
       rates below scale to the machine;
    2. **rebuild baseline** — ~15% capacity with incrementalEncode
       OFF: every flush pays the O(P) full arena rebuild (the
       `cycle_duration{phase="encode"}` mean is the rebuild cost).
       The fraction is deliberately conservative: the calibration
       burst runs depth-bounded (shedding keeps its backlog shallow),
       so its bind rate overstates what an UNBOUNDED leg sustains —
       a leg driven near that figure backlogs, the growing pending
       set flips the pad regime mid-drive, and the recompile stall
       degrades the watchdog ladder below `sequential`, gating off
       the very multi-cycle buffering (and admission-time ingest)
       this config measures;
    3. **incremental** — the SAME rate with incrementalEncode ON:
       ingest folds each acked pod in the ack path's shadow and the
       flush pays only the O(1) finalize — `encode_hidden_pct` is the
       share of encode host time that moved off the flush critical
       path, `finalize_p50_ms` the flush-side residue;
    4. **doubled rate** — incremental ON at 2x the rate: since the
       per-flush cost no longer scales with the backlog,
       `submit_bind_p50_ms` should stay flat (the ±20% acceptance
       rides `submit_bind_flat_pct`).

    All legs must shed nothing and lose nothing (sustained-load
    invariants, same as config 9). bench_diff gates the headline pair:
    `--max-finalize-rise` on finalize_p50_ms (lower is better) and
    `--min-encode-hidden` on encode_hidden_pct (higher is better)."""
    n_nodes = CONFIG_SHAPES[10][1]
    depth_bound = 64
    cal = front_door_drive(
        duration_s=1.5, rate_pps=400.0, n_nodes=n_nodes,
        batch=4, queue_depth=depth_bound, name_prefix="hec",
    )
    cap_pps = max(cal["bind_rate_pps"], 20.0)
    if cal["lost"] or cal["duplicate_binds"]:
        raise AssertionError(
            f"host_encode calibration violated invariants: "
            f"lost={cal['lost']} dup={cal['duplicate_binds']}"
        )
    leg_s = max(snapshots / 2.0, 4.0)
    base_rate = max(cap_pps * 0.15, 8.0)

    def leg(rate, inc, prefix):
        d = front_door_drive(
            duration_s=leg_s, rate_pps=rate, n_nodes=n_nodes,
            batch=4, name_prefix=prefix, incremental=inc,
        )
        if d["shed"] or d["lost"] or d["duplicate_binds"]:
            raise AssertionError(
                f"host_encode leg {prefix!r} violated invariants: "
                f"shed={d['shed']} lost={d['lost']} "
                f"dup={d['duplicate_binds']}"
            )
        m = d["sched"].metrics
        enc = m.cycle_duration.labels(phase="encode")
        out = {
            "binds": d["binds"], "acked": d["acked"],
            "wall_s": d["wall_s"], "accepted": d["accepted"],
            "encode_n": sum(b.get() for b in enc._buckets),
            "encode_sum_ms": enc._sum.get() * 1e3,
            "ingest_sum_ms": m.encode_ingest._sum.get() * 1e3,
            "finalize_sum_ms": m.encode_finalize._sum.get() * 1e3,
            "finalize_n": sum(
                b.get() for b in m.encode_finalize._buckets
            ),
            "finalize_samples_ms": sorted(
                r.phases["encode_finalize_ms"]
                for r in d["sched"].flight.snapshot()
                if "encode_finalize_ms" in r.phases
            ),
            "ingest_hits": sum(
                e.ingest_hits for e in d["sched"]._encoders.values()
            ),
            "ingest_misses": sum(
                e.ingest_misses for e in d["sched"]._encoders.values()
            ),
        }
        out["bind_p50_ms"] = _percentile(sorted(
            (t_bind - d["acked"][u]) * 1e3
            for u, (_c, t_bind) in d["binds"].items()
            if u in d["acked"]
        ), 50)
        return out

    off = leg(base_rate, inc=False, prefix="heo")
    on = leg(base_rate, inc=True, prefix="hei")
    on2 = leg(base_rate * 2.0, inc=True, prefix="he2")
    if not on["ingest_hits"]:
        raise AssertionError(
            "host_encode incremental leg never folded a staged ingest "
            f"row (misses={on['ingest_misses']}): the variant measured "
            "nothing but the fallback path"
        )

    ing, fin = on["ingest_sum_ms"], on["finalize_sum_ms"]
    hidden_pct = 100.0 * ing / max(ing + fin, 1e-9)
    rebuild_mean = off["encode_sum_ms"] / max(off["encode_n"], 1)
    finalize_mean = on["encode_sum_ms"] / max(on["encode_n"], 1)
    base_p50 = on["bind_p50_ms"]
    p50_2x = on2["bind_p50_ms"]
    return {
        "config": 10,
        "name": CONFIG_NAMES[10],
        "pods": off["accepted"] + on["accepted"] + on2["accepted"],
        "nodes": n_nodes,
        "snapshots": snapshots,
        "wall_s": round(
            cal["wall_s"] + off["wall_s"] + on["wall_s"]
            + on2["wall_s"], 2,
        ),
        "scheduled": (
            len(off["binds"]) + len(on["binds"]) + len(on2["binds"])
        ),
        "capacity_pps": round(cap_pps, 1),
        "rate_pps": round(base_rate, 1),
        # the headline pair bench_diff gates
        "encode_hidden_pct": round(hidden_pct, 2),
        "finalize_p50_ms": round(
            _percentile(on["finalize_samples_ms"], 50), 3
        ),
        # flush cadence + rebuild-vs-finalize cost (mean of the same
        # cycle_duration{phase="encode"} instrument on both legs)
        "flush_rate_per_s": round(
            on["finalize_n"] / max(on["wall_s"], 1e-9), 2
        ),
        "rebuild_mean_ms": round(rebuild_mean, 3),
        "finalize_mean_ms": round(finalize_mean, 3),
        "finalize_speedup": round(
            rebuild_mean / max(finalize_mean, 1e-9), 2
        ),
        "ingest_hits": on["ingest_hits"] + on2["ingest_hits"],
        "ingest_misses": on["ingest_misses"] + on2["ingest_misses"],
        # arrival-rate-doubling flatness: + = slower at 2x
        "submit_bind_p50_ms": round(base_p50, 3),
        "submit_bind_p50_2x_ms": round(p50_2x, 3),
        "submit_bind_flat_pct": round(
            100.0 * (p50_2x / max(base_p50, 1e-9) - 1.0), 1
        ),
    }


def _sharded_grid_env() -> "list[tuple[int, int]]":
    """Parse BENCH_SHARDED_GRID ("PxN,PxN,..."; default = the audit
    shape plus the 100k x 50k headline target). Parsed BEFORE any
    device work so a typo exits with the variable named."""
    default = "10000x5000,100000x50000"
    raw = os.environ.get("BENCH_SHARDED_GRID", default)
    out = []
    try:
        for part in raw.split(","):
            if not part.strip():
                continue
            p, n = part.lower().split("x")
            out.append((_pad(int(p)), _pad(int(n))))
    except ValueError as e:
        raise SystemExit(
            f"BENCH_SHARDED_GRID={raw!r} is not a comma list of PxN "
            f"pairs: {e}"
        ) from None
    if not out:
        raise SystemExit("BENCH_SHARDED_GRID parsed to an empty grid")
    return out


def run_sharded_scale_config(snapshots: int = 4) -> dict:
    """Config 8 (`sharded_scale`, ISSUE 10 / ROADMAP item 3): the carry
    cycle swept over device counts x a (pods, nodes) grid up to the
    100k x 50k headline geometry, sharded over a 1-D ('pods',) mesh.

    Per (grid point, device count): forced-sync per-cycle ms on
    device-resident buffers and the compiled program's collective
    payload (parallel/audit.py — the same parser the audit gate and the
    serving probe use). Headline keys, both gated directionally by
    scripts/bench_diff.py:

    - `scaling_efficiency` — t(1 device) / (t(D devices) * D) at the
      largest grid point that ran (drop = regressed);
    - `collective_payload_mb` — compiled payload per cycle at that
      point's max device count (rise = regressed).

    Grid points whose working set cannot fit the host's memory budget
    (BENCH_SHARDED_MEM_GB; default 60% of physical RAM — virtual CPU
    devices share one host) are skipped LOUDLY into `skipped[]`, never
    silently: on a single-chip rig the 100k x 50k row documents exactly
    why it needs the multi-chip deployment. Device counts come from
    BENCH_SHARDED_DEVICES (default "1,2,4,8") intersected with what the
    backend exposes; on a CPU backend the virtual-device flag is forced
    up front so the full sweep runs."""
    grid = _sharded_grid_env()
    try:
        dev_counts = sorted({
            max(int(x), 1)
            for x in os.environ.get(
                "BENCH_SHARDED_DEVICES", "1,2,4,8"
            ).split(",") if x.strip()
        })
    except ValueError as e:
        raise SystemExit(
            f"BENCH_SHARDED_DEVICES is not a comma list of ints: {e}"
        ) from None
    want = max(dev_counts)
    # CPU backend: force the virtual device count BEFORE first backend
    # use (same trick as __graft_entry__._force_virtual_cpu_mesh; on a
    # real accelerator the flag is inert and the sweep clips to the
    # chips that exist)
    if (
        os.environ.get("BENCH_FORCE_CPU") == "1"
        or os.environ.get("JAX_PLATFORMS", "") == "cpu"
    ):
        flag = f"--xla_force_host_platform_device_count={want}"
        if flag not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "") + " " + flag
            ).strip()
    import jax

    from k8s_scheduler_tpu.utils.compilation_cache import (
        enable_compilation_cache,
    )

    enable_compilation_cache()
    import numpy as np

    from k8s_scheduler_tpu.core import (
        build_packed_cycle_carry_fn,
        build_stable_state_fn,
    )
    from k8s_scheduler_tpu.core.cycle import CarryKeeper
    from k8s_scheduler_tpu.models import SnapshotEncoder
    from k8s_scheduler_tpu.parallel import audit
    from k8s_scheduler_tpu.parallel.mesh import make_mesh
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    avail = len(jax.devices())
    dev_counts = [d for d in dev_counts if d <= avail and 128 % d == 0]
    if not dev_counts:
        dev_counts = [1]
    try:
        page = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):
        page = 16 << 30
    mem_budget = float(
        os.environ.get("BENCH_SHARDED_MEM_GB", page * 0.6 / (1 << 30))
    ) * (1 << 30)

    rows: list[dict] = []
    skipped: list[dict] = []
    mb = 1024.0 * 1024.0
    for P, N in grid:
        # working-set model: the [P, N] f32 static base plus the round
        # engine's live [B, N]/[P, N] planes — ~16 bytes per (pod,
        # node) cell has held within 2x on the audit shape. Virtual CPU
        # devices share host RAM, so the budget is per HOST here; a
        # real multi-chip mesh divides by device count.
        est = P * N * 16
        if est > mem_budget:
            reason = (
                f"needs ~{est / (1 << 30):.1f} GiB working set vs "
                f"{mem_budget / (1 << 30):.1f} GiB budget "
                "(BENCH_SHARDED_MEM_GB) — run on a mesh whose devices "
                "hold it"
            )
            print(
                f"bench sharded_scale: SKIP {P}x{N}: {reason}",
                file=sys.stderr, flush=True,
            )
            skipped.append({"pods": P, "nodes": N, "reason": reason})
            continue
        n_real = min(N, max(N // 2, 1))
        pods_real = min(P, max(P // 2, 1))
        nodes = make_cluster(
            n_real, taint_fraction=0.1, cpu_choices=(4, 8, 16)
        )
        pending = make_pods(
            pods_real, seed=0, selector_fraction=0.3,
            toleration_fraction=0.1, priorities=(0, 0, 10, 100),
            num_apps=500,
        )
        enc = SnapshotEncoder(pad_pods=P, pad_nodes=N)
        t0 = time.perf_counter()
        wbuf, bbuf, spec, _vs, _dirty = enc.encode_packed(nodes, pending)
        encode_s = time.perf_counter() - t0
        point = {
            "pods": P, "nodes": N, "encode_s": round(encode_s, 2),
            "devices": {},
        }
        base_assign = None
        for d in dev_counts:
            mesh = make_mesh(jax.devices()[:d]) if d > 1 else None
            cyc = build_packed_cycle_carry_fn(
                spec, mesh=mesh,
                rounds_kw=(
                    {"compact_gather": "onehot"} if mesh is not None
                    else None
                ),
            )
            keeper = CarryKeeper(spec, mesh=mesh)
            stable = build_stable_state_fn(spec)(wbuf, bbuf)
            w = jax.device_put(wbuf)
            b = jax.device_put(bbuf)
            t0 = time.perf_counter()
            carry = keeper.ci(w, b, stable)
            out = cyc(w, b, stable, carry)
            a = np.asarray(out.assignment)
            compile_s = time.perf_counter() - t0
            if base_assign is None:
                base_assign = a
            elif not (a == base_assign).all():
                raise AssertionError(
                    f"sharded_scale {P}x{N}: {d}-device placements "
                    "diverged from the 1-device run — the shard-"
                    "invariance contract is broken"
                )
            times = []
            for _ in range(max(snapshots, 2)):
                t0 = time.perf_counter()
                out = cyc(w, b, stable, carry)
                np.asarray(out.assignment)
                times.append(time.perf_counter() - t0)
            payload = 0
            try:
                payload = audit.collective_payload_bytes(
                    cyc.lower(w, b, stable, carry).compile().as_text()
                )
            except Exception as e:  # accounting only, never the sweep
                print(
                    f"bench sharded_scale: payload probe failed at "
                    f"{P}x{N}/d{d}: {e}", file=sys.stderr, flush=True,
                )
            point["devices"][str(d)] = {
                "per_device_ms": round(_percentile(times, 50) * 1e3, 2),
                "compile_s": round(compile_s, 2),
                "collective_payload_mb": round(payload / mb, 3),
            }
        ds = point["devices"]
        if "1" in ds and len(ds) > 1:
            dmax = str(max(int(k) for k in ds))
            t1 = ds["1"]["per_device_ms"]
            td = ds[dmax]["per_device_ms"]
            point["scaling_efficiency"] = round(
                t1 / max(td * int(dmax), 1e-9), 3
            )
            point["speedup"] = round(t1 / max(td, 1e-9), 2)
        rows.append(point)

    if not rows:
        raise SystemExit(
            "sharded_scale: every grid point was skipped — lower "
            "BENCH_SHARDED_GRID or raise BENCH_SHARDED_MEM_GB"
        )
    head = rows[-1]  # largest grid point that ran
    dmax = str(max(int(k) for k in head["devices"]))
    return {
        "config": 8,
        "name": CONFIG_NAMES[8],
        "pods": head["pods"],
        "nodes": head["nodes"],
        "snapshots": snapshots,
        "device_counts": dev_counts,
        "grid": rows,
        "skipped": skipped,
        "per_device_ms": head["devices"][dmax]["per_device_ms"],
        "collective_payload_mb": (
            head["devices"][dmax]["collective_payload_mb"]
        ),
        **(
            {"scaling_efficiency": head["scaling_efficiency"]}
            if "scaling_efficiency" in head else
            # a single-chip host cannot measure scaling; 1.0 keeps the
            # key present (and bench_diff comparable) without
            # fabricating a speedup
            {"scaling_efficiency": 1.0}
        ),
    }


def run_tenant_arena_config(snapshots: int = 12) -> dict:
    """Config 11: the multi-tenant arena headline (ISSUE 18) — T small
    same-spec virtual clusters scheduled by ONE compiled program per
    cycle vs T sequential single-tenant dispatches of the same packed
    program.

    Steady-state protocol: every wave feeds each tenant the same-shape
    pod batch, runs one fleet cycle on BOTH legs, asserts the decision
    streams bit-equal (the isolation property IS the bench's validity),
    then retires every decided pod so shapes never drift between
    waves. The first wave is warmup (compiles both legs); the timed
    window must create ZERO new arena executables — `arena_warm_builds`
    is the bench_diff gate for that, `arena_speedup` (sequential wall /
    packed wall) the headline.

    Env: BENCH_TENANTS (default 64; the ISSUE headline runs 1000),
    BENCH_TENANT_NODES / BENCH_TENANT_PODS (per-tenant shape, default
    4x6), BENCH_TENANT_SEQ=0 skips the sequential leg (packed-only
    soak; speedup omitted).
    """
    from k8s_scheduler_tpu.tenancy import MultiTenantArena, TenantRegistry
    from k8s_scheduler_tpu.utils.synth import make_cluster, make_pods

    T = int(os.environ.get("BENCH_TENANTS", 64))
    nodes_per = int(os.environ.get("BENCH_TENANT_NODES", 4))
    pods_per = int(os.environ.get("BENCH_TENANT_PODS", 6))
    with_seq = os.environ.get("BENCH_TENANT_SEQ", "1") != "0"
    waves = max(int(snapshots), 3)
    tids = [f"vc-{i:04d}" for i in range(T)]

    def retenant(objs, tid):
        for o in objs:
            o.metadata.namespace = tid
            o.metadata.uid = f"{tid}/{o.metadata.name}"
        return objs

    def build():
        reg = TenantRegistry()
        for tid in tids:
            reg.create(tid)
            # one node seed fleet-wide: identical shapes keep every
            # tenant in ONE spec bucket (the headline packing regime)
            for nd in retenant(make_cluster(nodes_per, seed=7), tid):
                reg.add_node(tid, nd)
        return reg

    def feed(reg, wave):
        for tid in tids:
            for p in retenant(
                make_pods(
                    pods_per, seed=1000 + wave,
                    name_prefix=f"w{wave}",
                ),
                tid,
            ):
                reg.add_pod(tid, p)

    def retire(reg, arena):
        # every decided pod leaves (bound pods "complete", losers kick
        # back to their owner): per-tenant shapes are identical every
        # wave, so the timed window can never cross a pad bucket
        for tid, uid, _node in arena.last_decisions:
            reg.remove_pod(tid, uid)

    legs = [("packed", build(), False)]
    if with_seq:
        legs.append(("sequential", build(), True))
    arenas = {
        name: MultiTenantArena(reg, sequential=seq)
        for name, reg, seq in legs
    }
    regs = {name: reg for name, reg, _seq in legs}

    # warmup wave: compiles on both legs, not timed
    for name in arenas:
        feed(regs[name], 0)
        arenas[name].run_cycle()
    builds_warm = arenas["packed"].packer.builds
    for name in arenas:
        retire(regs[name], arenas[name])

    wall: dict[str, list] = {name: [] for name in arenas}
    device: dict[str, float] = {name: 0.0 for name in arenas}
    bound = {name: 0 for name in arenas}
    divergences = 0
    for wave in range(1, waves + 1):
        streams = {}
        for name in arenas:
            feed(regs[name], wave)
            t0 = time.perf_counter()
            stats = arenas[name].run_cycle()
            wall[name].append(time.perf_counter() - t0)
            device[name] += stats["device_s"]
            bound[name] += stats["bound"]
            streams[name] = sorted(arenas[name].last_decisions)
            retire(regs[name], arenas[name])
        if with_seq and streams["packed"] != streams["sequential"]:
            divergences += 1  # the property failing IS the headline

    packed_s = sum(wall["packed"])
    packed_ms = [v * 1e3 for v in wall["packed"]]
    pods_wave = T * pods_per
    out = {
        "config": 11,
        "name": "tenant_arena",
        "tenants": T,
        "nodes_per_tenant": nodes_per,
        "pods_per_tenant": pods_per,
        "waves": waves,
        "pods_per_wave": pods_wave,
        "bound": bound["packed"],
        "divergent_waves": divergences,
        "arena_dispatches": arenas["packed"].packer.dispatches,
        "arena_builds": arenas["packed"].packer.builds,
        # executables created INSIDE the timed window — the
        # zero-compiles-after-warmup gate (bench_diff
        # --max-arena-warm-builds, default 0)
        "arena_warm_builds": arenas["packed"].packer.builds - builds_warm,
        "tenants_per_dispatch": round(
            arenas["packed"].packer.tenants_packed
            / max(arenas["packed"].packer.dispatches, 1), 2,
        ),
        "packed_cycle_p50_ms": round(_percentile(packed_ms, 50), 3),
        "packed_cycle_p99_ms": round(_percentile(packed_ms, 99), 3),
        "packed_device_ms": round(device["packed"] * 1e3 / waves, 3),
        "pods_per_sec_packed": round(
            pods_wave * waves / max(packed_s, 1e-9), 1,
        ),
        "decisions_per_sec": round(
            pods_wave * nodes_per * waves / max(packed_s, 1e-9), 1,
        ),
    }
    if with_seq:
        seq_s = sum(wall["sequential"])
        seq_ms = [v * 1e3 for v in wall["sequential"]]
        out.update({
            "seq_cycle_p50_ms": round(_percentile(seq_ms, 50), 3),
            "seq_device_ms": round(
                device["sequential"] * 1e3 / waves, 3,
            ),
            "pods_per_sec_sequential": round(
                pods_wave * waves / max(seq_s, 1e-9), 1,
            ),
            # end-to-end cycle speedup: includes the per-tenant host
            # encode/fold BOTH legs pay identically, so at high T this
            # converges to the host-bound floor, not the device ratio
            "arena_speedup": round(seq_s / max(packed_s, 1e-9), 2),
            # device-window speedup: T launches + fetches vs one — the
            # dispatch amortization the arena actually buys (on real
            # accelerators the per-launch tunnel round trip makes this
            # the serving-path headline; on CPU smoke it is the
            # launch-overhead ratio)
            "arena_device_speedup": round(
                device["sequential"] / max(device["packed"], 1e-9), 2,
            ),
        })
    return out


def run_suite(configs=(1, 2, 3, 4, 5), snapshots: int = 50) -> list[dict]:
    return [run_config(c, snapshots=snapshots) for c in configs]


def main() -> None:
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    configs = [int(a) for a in sys.argv[1:]] or [1, 2, 3, 4, 5]
    snapshots = int(os.environ.get("BENCH_SNAPSHOTS", 50))
    for c in configs:
        print(json.dumps(run_config(c, snapshots=snapshots)), flush=True)


if __name__ == "__main__":
    main()
