#!/usr/bin/env python
"""scheduler_perf-style benchmark suite: the five BASELINE configs with
feature-realistic synthetic workloads and latency percentiles.

The model is upstream's `test/integration/scheduler_perf/` (SURVEY.md §4,
§7 step 8): drive thousands of synthetic pods/nodes through the scheduler
and record throughput plus latency percentiles. Each config here runs
`BENCH_SNAPSHOTS` DISTINCT snapshots (pending pods re-drawn per cycle, so
jit-cache behaviour is what steady serving sees) through the fused cycle —
plus, for config #4, the PostFilter/preemption pass whenever pods are left
unschedulable, and for config #5, gang all-or-nothing unwinds.

Emits one JSON line per config:
    {"config": 4, "name": "full_default_preemption", "decisions_per_sec":…,
     "p50_ms":…, "p99_ms":…, "scheduled":…, "preemptors":…, …}

Used by bench.py (which reports the driver's single headline line) and
runnable standalone:  BENCH_SNAPSHOTS=10 python bench_suite.py 1 4
"""

from __future__ import annotations

import json
import os
import sys
import time


def _percentile(xs: list[float], q: float) -> float:
    ys = sorted(xs)
    if not ys:
        return 0.0
    k = min(len(ys) - 1, max(0, int(round(q / 100.0 * (len(ys) - 1)))))
    return ys[k]


def _pad(n: int, b: int = 128) -> int:
    return ((n + b - 1) // b) * b


def make_config_base(cfg: int):
    """(nodes, existing, groups_unused) — the STABLE cluster for `cfg`,
    generated once per run: in steady serving the node and running-pod
    objects persist across cycles (the scheduler's cache holds them), so
    the encoder's per-object row cache applies; only the pending set is
    fresh each cycle."""
    nodes, _pods, existing, _groups = make_config_workload(cfg, seed=0)
    return nodes, existing


def make_config_workload(cfg: int, seed: int):
    """(nodes, pending, existing, groups) for BASELINE config `cfg`; `seed`
    re-draws the pending set so every snapshot is distinct."""
    from k8s_scheduler_tpu.utils.synth import (
        make_cluster,
        make_gang_pods,
        make_pods,
    )

    if cfg == 1:  # 100 pods x 10 nodes, CPU/mem requests only
        return make_cluster(10, with_labels=False), make_pods(100, seed=seed), [], []
    if cfg == 2:  # 1k pods x 100 nodes, node-affinity + taints/tolerations
        nodes = make_cluster(100, taint_fraction=0.3)
        pods = make_pods(
            1000, seed=seed, selector_fraction=0.5, toleration_fraction=0.4
        )
        return nodes, pods, [], []
    if cfg == 3:  # 5k pods x 1k nodes, inter-pod (anti-)affinity
        nodes = make_cluster(1000)
        pods = make_pods(
            5000,
            seed=seed,
            affinity_fraction=0.3,
            anti_affinity_fraction=0.2,
            spread_fraction=0.2,
            num_apps=500,
        )
        return nodes, pods, [], []
    if cfg == 4:  # 10k pods x 5k nodes, full default plugin set + preemption
        # small nodes + a low-priority existing workload occupying most
        # capacity: high-priority pending pods must preempt, low-priority
        # ones go unschedulable — the PostFilter pass has real work
        nodes = make_cluster(5000, taint_fraction=0.1, cpu_choices=(4, 8, 16))
        existing_pods = make_pods(
            12000,
            seed=991,  # fixed: the running cluster is stable across cycles
            name_prefix="run",
            affinity_fraction=0.1,
            spread_fraction=0.1,
            num_apps=500,
        )
        existing = [
            (p, f"node-{i % 5000}") for i, p in enumerate(existing_pods)
        ]
        pods = make_pods(
            10000,
            seed=seed,
            affinity_fraction=0.3,
            anti_affinity_fraction=0.2,
            spread_fraction=0.2,
            selector_fraction=0.3,
            toleration_fraction=0.1,
            priorities=(0, 0, 10, 100),
            num_apps=500,
        )
        return nodes, pods, existing, []
    if cfg == 5:  # gang-schedule 1k 8-replica jobs on 2k nodes
        # capacity below aggregate demand: the tail of the priority order
        # cannot fully place, so all-or-nothing unwinds really fire
        nodes = make_cluster(2000, cpu_choices=(8,))
        pods, groups = make_gang_pods(1000, replicas=8, seed=seed)
        return nodes, pods, [], groups
    raise ValueError(f"unknown config {cfg}")


CONFIG_NAMES = {
    1: "resources_only",
    2: "labels_taints",
    3: "interpod_affinity",
    4: "full_default_preemption",
    5: "gang_coscheduling",
}
CONFIG_SHAPES = {1: (100, 10), 2: (1000, 100), 3: (5000, 1000),
                 4: (10000, 5000), 5: (8000, 2000)}


def run_config(cfg: int, snapshots: int = 50) -> dict:
    import jax
    import numpy as np

    from k8s_scheduler_tpu.core import build_cycle_fn, build_preemption_fn
    from k8s_scheduler_tpu.models import SnapshotEncoder

    P_real, N_real = CONFIG_SHAPES[cfg]
    # the round-based batched commit is the production engine; the strict
    # sequential scan is available for comparison via BENCH_COMMIT_MODE
    mode = os.environ.get("BENCH_COMMIT_MODE", "rounds")
    cycle = build_cycle_fn(commit_mode=mode)
    preempt = build_preemption_fn() if cfg == 4 else None

    # one encoder across snapshots keeps the string/selector dictionaries
    # stable (what a long-lived serving process sees)
    enc = SnapshotEncoder(pad_pods=_pad(P_real), pad_nodes=_pad(N_real))

    # Timing methodology: on this rig the TPU sits behind a tunnel with a
    # measured ~90ms fixed dispatch+read round-trip, and async dispatch
    # reports readiness optimistically — block_until_ready alone massively
    # under-reports. Every timed region therefore ends with a FORCING
    # device->host read (np.asarray of a small output), and the fixed
    # read round-trip (measured on an already-ready buffer) is subtracted.
    times: list[float] = []
    encode_times: list[float] = []
    compile_s = 0.0
    d2h_s = 0.0
    shape_keys: set = set()
    totals = {"scheduled": 0, "unschedulable": 0, "gang_dropped": 0,
              "preemptors": 0, "victims": 0}
    base_nodes, base_existing = make_config_base(cfg)
    for i in range(snapshots):
        _n, pods, _e, groups = make_config_workload(cfg, seed=1000 + i)
        t0 = time.perf_counter()
        snap = enc.encode(base_nodes, pods, base_existing, groups)
        encode_times.append(time.perf_counter() - t0)
        key = tuple(
            (k, v.shape) for k, v in sorted(snap.array_fields().items())
        )
        if key not in shape_keys:
            # first sight of this padded shape: compile + sync (warmup,
            # untimed as cycle latency — reported separately)
            shape_keys.add(key)
            t0 = time.perf_counter()
            out = cycle(snap)
            np.asarray(out.assignment)
            if preempt is not None:
                pre = preempt(snap, out)
                np.asarray(pre.nominated)
            compile_s += time.perf_counter() - t0
            # fixed D2H round-trip on a ready buffer (subtracted below)
            t0 = time.perf_counter()
            np.asarray(out.assignment)
            d2h_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        out = cycle(snap)
        pre = None
        if preempt is not None:
            # preemption chains on the cycle output device-side; one
            # forcing read at the end times the whole attempt
            pre = preempt(snap, out)
            np.asarray(pre.nominated)
        a = np.asarray(out.assignment)
        times.append(max(time.perf_counter() - t0 - d2h_s, 0.0))
        if os.environ.get("BENCH_DEBUG"):
            print(f"  iter={i} cycle={times[-1]:.4f}s", flush=True)

        valid = np.asarray(snap.pod_valid)
        totals["scheduled"] += int(((a >= 0) & valid).sum())
        totals["unschedulable"] += int(np.asarray(out.unschedulable).sum())
        totals["gang_dropped"] += int(np.asarray(out.gang_dropped).sum())
        if pre is not None and totals["unschedulable"]:
            totals["preemptors"] += int(np.asarray(pre.num_preemptors))
            totals["victims"] += int(np.asarray(pre.victims).sum())

    p50 = _percentile(times, 50)
    p99 = _percentile(times, 99)
    return {
        "config": cfg,
        "commit_mode": mode,
        "name": CONFIG_NAMES[cfg],
        "pods": P_real,
        "nodes": N_real,
        "snapshots": snapshots,
        "decisions_per_sec": round(P_real * N_real / max(p50, 1e-9), 1),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
        "d2h_roundtrip_ms": round(d2h_s * 1e3, 3),
        "encode_p50_ms": round(_percentile(encode_times, 50) * 1e3, 3),
        "compile_seconds": round(compile_s, 2),
        "distinct_shapes": len(shape_keys),
        **{k: v // max(snapshots, 1) for k, v in totals.items()},
    }


def run_suite(configs=(1, 2, 3, 4, 5), snapshots: int = 50) -> list[dict]:
    return [run_config(c, snapshots=snapshots) for c in configs]


def main() -> None:
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    configs = [int(a) for a in sys.argv[1:]] or [1, 2, 3, 4, 5]
    snapshots = int(os.environ.get("BENCH_SNAPSHOTS", 50))
    for c in configs:
        print(json.dumps(run_config(c, snapshots=snapshots)), flush=True)


if __name__ == "__main__":
    main()
